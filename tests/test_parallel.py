"""JAX layer on the 8-device CPU mesh: mesh solving, collectives, flash and
ring attention numerics, sharded train step."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from tpu_composer.models.transformer import ModelConfig, forward, init_params, loss_fn
from tpu_composer.ops.attention import flash_attention, mha_reference
from tpu_composer.parallel import (
    allreduce_bandwidth_gbps,
    make_mesh,
    make_train_state,
    make_train_step,
    ring_attention,
    solve_mesh_axes,
    TrainConfig,
    ring_attention_zigzag,
)


class TestMeshSolver:
    def test_solve_8(self):
        assert solve_mesh_axes(8) == {"dp": 1, "sp": 1, "tp": 8}

    def test_fixed_degrees(self):
        assert solve_mesh_axes(8, dp=2, sp=2, tp=2) == {"dp": 2, "sp": 2, "tp": 2}

    def test_indivisible_rejected(self):
        with pytest.raises(ValueError):
            solve_mesh_axes(8, tp=3)

    def test_make_mesh_axes(self):
        mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
        assert mesh.axis_names == ("dp", "sp", "tp")
        assert mesh.devices.shape == (2, 2, 2)

    def test_make_mesh_wrong_count(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 16})


class TestCollectives:
    def test_allreduce_bandwidth_runs_and_is_positive(self):
        mesh = make_mesh({"x": 8})
        bw = allreduce_bandwidth_gbps(mesh, size_mb=1.0, iters=2)
        assert bw > 0

    def test_single_device_reports_zero(self):
        mesh = make_mesh({"x": 1}, devices=jax.devices()[:1])
        assert allreduce_bandwidth_gbps(mesh, size_mb=1.0) == 0.0


def rand_qkv(key, b=2, s=128, h=4, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (
        jax.random.normal(kq, shape, dtype),
        jax.random.normal(kk, shape, dtype),
        jax.random.normal(kv, shape, dtype),
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = rand_qkv(jax.random.key(0))
        ref = mha_reference(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_blocks_must_divide(self):
        q, k, v = rand_qkv(jax.random.key(0), s=100)
        with pytest.raises(ValueError):
            flash_attention(q, k, v, block_q=64, block_k=64)

    def test_bf16_path(self):
        q, k, v = rand_qkv(jax.random.key(1), dtype=jnp.bfloat16)
        ref = mha_reference(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        np.testing.assert_allclose(
            out.astype(np.float32), ref.astype(np.float32), atol=3e-2, rtol=3e-2
        )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference_over_ring(self, causal):
        mesh = make_mesh({"sp": 8})
        b, s, h, d = 2, 256, 4, 32
        q, k, v = rand_qkv(jax.random.key(2), b=b, s=s, h=h, d=d)

        ring = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=causal),
            mesh=mesh,
            in_specs=(P(None, "sp", None, None),) * 3,
            out_specs=P(None, "sp", None, None),
            check_vma=False,
        )
        spec = NamedSharding(mesh, P(None, "sp", None, None))
        out = ring(
            jax.device_put(q, spec), jax.device_put(k, spec), jax.device_put(v, spec)
        )
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_jit_compiles_ring(self):
        mesh = make_mesh({"sp": 4}, devices=jax.devices()[:4])
        q, k, v = rand_qkv(jax.random.key(3), s=128)
        fn = jax.jit(
            shard_map(
                functools.partial(ring_attention, axis_name="sp", causal=True),
                mesh=mesh,
                in_specs=(P(None, "sp", None, None),) * 3,
                out_specs=P(None, "sp", None, None),
                check_vma=False,
            )
        )
        out = fn(q, k, v)
        assert out.shape == q.shape


class TestModel:
    def small_config(self, **kw):
        defaults = dict(
            vocab_size=128, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq=64, dtype=jnp.float32,
        )
        defaults.update(kw)
        return ModelConfig(**defaults)

    def test_forward_shapes_and_finite(self):
        c = self.small_config()
        params = init_params(c, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, c.vocab_size)
        logits = forward(params, tokens, c)
        assert logits.shape == (2, 32, c.vocab_size)
        assert bool(jnp.isfinite(logits).all())

    def test_loss_decreases_under_sgd(self):
        c = self.small_config()
        params = init_params(c, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, c.vocab_size)
        grad_fn = jax.jit(jax.value_and_grad(lambda p: loss_fn(p, tokens, c)))
        loss0, grads = grad_fn(params)
        for _ in range(5):
            loss, grads = grad_fn(params)
            params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
        loss_end, _ = grad_fn(params)
        assert loss_end < loss0

    def test_causality(self):
        """Changing a future token must not change past logits."""
        c = self.small_config()
        params = init_params(c, jax.random.key(0))
        t1 = jax.random.randint(jax.random.key(1), (1, 16), 0, c.vocab_size)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % c.vocab_size)
        l1 = forward(params, t1, c)
        l2 = forward(params, t2, c)
        np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)

    def test_flash_impl_matches_reference_forward(self):
        c = self.small_config(attn_impl="flash", max_seq=64)
        cr = self.small_config(attn_impl="reference")
        params = init_params(c, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, c.vocab_size)
        lf = forward(params, tokens, c)
        lr = forward(params, tokens, cr)
        np.testing.assert_allclose(lf, lr, atol=1e-4, rtol=1e-4)


class TestShardedTrainStep:
    def test_full_step_on_dp_sp_tp_mesh(self):
        mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
        tc = TrainConfig(
            model=ModelConfig(
                vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
                max_seq=64, dtype=jnp.float32,
            )
        )
        state = make_train_state(tc, jax.random.key(0), mesh)
        step_fn, batch_sharding = make_train_step(tc, mesh)
        tokens = jax.device_put(
            jax.random.randint(jax.random.key(1), (4, 64), 0, 256), batch_sharding
        )
        state, metrics = step_fn(state, tokens)
        assert bool(jnp.isfinite(metrics["loss"]))
        state, metrics2 = step_fn(state, tokens)
        assert metrics2["loss"] < metrics["loss"]  # it learns the batch

    def test_grad_accumulation_matches_full_batch(self):
        """Mean-reduced loss over equal microbatches == the full-batch
        mean, so accum=4 must produce the SAME update as accum=1 on the
        same global batch (float-association tolerance)."""
        mc = ModelConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq=32, dtype=jnp.float32,
        )
        mesh = make_mesh({"dp": 2, "sp": 1, "tp": 4})
        tokens = jax.random.randint(jax.random.key(1), (8, 32), 0, 256)
        tc1 = TrainConfig(model=mc)
        tc4 = TrainConfig(model=mc, grad_accum_steps=4)
        s1 = make_train_state(tc1, jax.random.key(0), mesh)
        s4 = make_train_state(tc4, jax.random.key(0), mesh)
        step1, bs = make_train_step(tc1, mesh)
        step4, _ = make_train_step(tc4, mesh)
        tokens = jax.device_put(tokens, bs)
        s1, m1 = step1(s1, tokens)
        s4, m4 = step4(s4, tokens)
        np.testing.assert_allclose(float(m1["loss"]), float(m4["loss"]),
                                   rtol=1e-5)
        np.testing.assert_allclose(float(m1["grad_norm"]),
                                   float(m4["grad_norm"]), rtol=1e-4)
        flat1 = jax.tree_util.tree_leaves(s1["params"])
        flat4 = jax.tree_util.tree_leaves(s4["params"])
        for a, b in zip(flat1, flat4):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-6)

    def test_grad_accum_must_divide_batch(self):
        mc = ModelConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=2,
                         d_ff=64, max_seq=16, dtype=jnp.float32)
        mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1},
                         devices=jax.devices()[:1])
        tc = TrainConfig(model=mc, grad_accum_steps=3)
        state = make_train_state(tc, jax.random.key(0), mesh)
        step_fn, bs = make_train_step(tc, mesh)
        tokens = jax.device_put(
            jax.random.randint(jax.random.key(1), (4, 16), 0, 64), bs)
        with pytest.raises(ValueError, match="not divisible"):
            step_fn(state, tokens)

    def test_ring_and_plain_attention_agree_in_training(self):
        mc = ModelConfig(
            vocab_size=256, d_model=64, n_layers=1, n_heads=4, d_ff=128,
            max_seq=64, dtype=jnp.float32,
        )
        mesh = make_mesh({"dp": 1, "sp": 8, "tp": 1})
        tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, 256)

        tc_ring = TrainConfig(model=mc, use_ring_attention=True)
        tc_ref = TrainConfig(model=mc, use_ring_attention=False)
        s_ring = make_train_state(tc_ring, jax.random.key(0), mesh)
        s_ref = make_train_state(tc_ref, jax.random.key(0), mesh)
        step_ring, bs = make_train_step(tc_ring, mesh)
        step_ref, _ = make_train_step(tc_ref, mesh)
        tokens = jax.device_put(tokens, bs)
        _, m_ring = step_ring(s_ring, tokens)
        _, m_ref = step_ref(s_ref, tokens)
        np.testing.assert_allclose(
            float(m_ring["loss"]), float(m_ref["loss"]), atol=1e-4, rtol=1e-4
        )


class TestAcceptance:
    def test_qualify_slice_on_cpu_mesh(self):
        from tpu_composer.models.transformer import ModelConfig
        from tpu_composer.workload.acceptance import qualify_slice

        res = qualify_slice(
            mesh=make_mesh({"dp": 2, "sp": 2, "tp": 2}),
            batch=2, seq=64, allreduce_mb=1.0, steps=1,
            model_config=ModelConfig(
                vocab_size=256, d_model=64, n_layers=1, n_heads=4, d_ff=128,
                max_seq=64, dtype=jnp.float32,
            ),
        )
        assert res["n_devices"] == 8.0
        assert res["allreduce_gbps"] > 0
        assert res["tokens_per_s"] > 0
        assert np.isfinite(res["train_loss"])


class TestZigzagRingAttention:
    """Compute-balanced causal ring attention: same contiguous contract as
    ring_attention, zigzag redistribution inside. Numerics must match the
    full-attention reference exactly, forward AND backward."""

    def _shard(self, fn, mesh):

        spec = P(None, "sp", None, None)
        return shard_map(fn, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                         check_vma=False)

    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_reference(self, sp):

        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, d = 2, 16 * sp, 2, 32
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)

        zz = self._shard(
            functools.partial(ring_attention_zigzag, axis_name="sp",
                              causal=True),
            mesh,
        )
        out = zz(q, k, v)
        ref = mha_reference(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 2e-5

    def test_gradients_match_reference(self):

        sp = 4
        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, d = 1, 8 * sp, 2, 16
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)
        zz = self._shard(
            functools.partial(ring_attention_zigzag, axis_name="sp",
                              causal=True),
            mesh,
        )
        g_zz = jax.grad(lambda *a: zz(*a).sum(), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda *a: mha_reference(*a, causal=True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        err = max(float(jnp.abs(a - b).max()) for a, b in zip(g_zz, g_ref))
        assert err < 2e-5

    def test_noncausal_delegates(self):

        mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])
        b, s, h, d = 1, 32, 2, 16
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)
        zz = self._shard(
            functools.partial(ring_attention_zigzag, axis_name="sp",
                              causal=False),
            mesh,
        )
        ref = mha_reference(q, k, v, causal=False)
        assert float(jnp.abs(zz(q, k, v) - ref).max()) < 2e-5


class TestRingFlashInner:
    """inner="flash": the Pallas kernel per ring block, merged via its
    logsumexp output. Parity vs the same full-attention reference the
    einsum inner is held to — forward and backward, GQA included (the
    flash inner rotates UN-repeated grouped K/V)."""

    def _shard(self, fn, mesh, kv_spec=None):
        spec = P(None, "sp", None, None)
        return shard_map(fn, mesh=mesh,
                         in_specs=(spec, kv_spec or spec, kv_spec or spec),
                         out_specs=spec, check_vma=False)

    @pytest.mark.parametrize("variant,causal", [
        (ring_attention, False),
        (ring_attention, True),
        (ring_attention_zigzag, True),
    ])
    def test_matches_reference(self, variant, causal):
        sp = 4
        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, d = 1, 32 * sp, 4, 32
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)
        fn = self._shard(
            functools.partial(variant, axis_name="sp", causal=causal,
                              inner="flash"),
            mesh,
        )
        ref = mha_reference(q, k, v, causal=causal)
        assert float(jnp.abs(fn(q, k, v) - ref).max()) < 2e-5

    def test_gqa_rotates_grouped_kv(self):
        sp = 4
        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, hk, d = 1, 16 * sp, 4, 2, 32
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, hk, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, hk, d), jnp.float32)
        fn = self._shard(
            functools.partial(ring_attention, axis_name="sp", causal=True,
                              inner="flash"),
            mesh,
        )
        ref = mha_reference(q, k, v, causal=True)
        assert float(jnp.abs(fn(q, k, v) - ref).max()) < 2e-5

    def test_gradients_match_reference(self):
        sp = 2
        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, d = 1, 16 * sp, 2, 16
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)
        fn = self._shard(
            functools.partial(ring_attention, axis_name="sp", causal=True,
                              inner="flash"),
            mesh,
        )
        g_f = jax.grad(lambda *a: fn(*a).sum(), argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(
            lambda *a: mha_reference(*a, causal=True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        err = max(float(jnp.abs(a - b).max()) for a, b in zip(g_f, g_r))
        assert err < 2e-5

    def test_unknown_inner_rejected(self):
        with pytest.raises(ValueError):
            ring_attention(None, None, None, axis_name="sp", inner="bogus")


class TestTrainStepFlashInner:
    def test_first_step_matches_einsum_inner(self):
        """The sp_inner choice is an implementation detail: one train step
        from identical init must produce the same loss (fp32 tolerance)
        with the flash inner as with the einsum inner."""
        from tpu_composer.parallel import (
            make_train_state,
            make_train_step,
            solve_mesh_axes,
        )

        axes = solve_mesh_axes(4, sp=2, tp=2)
        mesh = make_mesh(axes, devices=jax.devices()[:4])
        mc = ModelConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, d_ff=128, max_seq=32,
                         dtype=jnp.float32)
        losses = {}
        for inner in ("einsum", "flash"):
            tc = TrainConfig(model=mc, sp_impl="ring", sp_inner=inner)
            state = make_train_state(tc, jax.random.key(0), mesh)
            step_fn, batch_sharding = make_train_step(tc, mesh)
            tokens = jax.device_put(
                jax.random.randint(jax.random.key(1), (4, 32), 0, 128),
                batch_sharding,
            )
            _, metrics = step_fn(state, tokens)
            losses[inner] = float(metrics["loss"])
        assert abs(losses["flash"] - losses["einsum"]) < 1e-4, losses

    def test_flash_inner_rejected_with_pipelining(self):
        from tpu_composer.parallel import make_train_step, solve_mesh_axes

        axes = solve_mesh_axes(4, pp=2, sp=2)
        mesh = make_mesh(axes, devices=jax.devices()[:4])
        tc = TrainConfig(
            model=ModelConfig(vocab_size=128, d_model=64, n_layers=2,
                              n_heads=4, d_ff=128, max_seq=32),
            pipeline_microbatches=2, sp_inner="flash",
        )
        with pytest.raises(ValueError, match="pipeline"):
            make_train_step(tc, mesh)


    def test_zigzag_flash_gradients_match_reference(self):
        """Backward parity for the balanced long-context path: the merge's
        lse gradient must differentiate correctly under zigzag's per-half
        cond/ppermute structure, not just compile."""
        sp = 4
        mesh = make_mesh({"sp": sp}, devices=jax.devices()[:sp])
        b, s, h, d = 1, 8 * sp, 2, 16
        q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
        k = jax.random.normal(jax.random.key(1), (b, s, h, d), jnp.float32)
        v = jax.random.normal(jax.random.key(2), (b, s, h, d), jnp.float32)
        spec = P(None, "sp", None, None)
        fn = shard_map(
            functools.partial(ring_attention_zigzag, axis_name="sp",
                              causal=True, inner="flash"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )
        g_f = jax.grad(lambda *a: fn(*a).sum(), argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(
            lambda *a: mha_reference(*a, causal=True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        err = max(float(jnp.abs(a - b).max()) for a, b in zip(g_f, g_r))
        assert err < 2e-5
