"""Asymmetric-partition soak (ISSUE 20 tentpole).

Every crash soak before this one killed processes — the kernel closed the
sockets and told the peers. This soak makes the NETWORK lie instead: a
3-replica ProcFleet runs seeded churn with each replica's store wire
routed through its own TCP chaos proxy (sim/netchaos.py), and the busiest
replica gets an ASYMMETRIC partition — its requests still land on the
apiserver, but every response goes dark (``partition("s2c")``). That is
the nastiest partition class: the victim's writes apply server-side while
the victim itself sees only silence, so naive retry would double-submit
and naive liveness would never fire.

What must hold:

- the victim's mux detects the dark wire by ping deadline (seconds, not
  the 30s per-request baseline) and fails everything pending at once;
- survivors steal the victim's shard leases within the takeover bound;
- the victim FENCES: the supervisor-side fabric mutation ledger
  (X-Tpuc-Replica attribution, monotonic timestamps) shows no fabric
  mutation by the victim past ``t_partition + renew_deadline + slack``;
- after ``heal()`` the fleet converges — every surviving request Running,
  zero pending intents, the victim process alive the whole time (store
  outage ride-through, no crash) — and the pool's nonce-stamped event
  ring shows zero double-attach across the handoff.

Run: ``make partition-soak`` (markers slow+partition).
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from tpu_composer import GROUP, VERSION
from tpu_composer.fleet.proc import ProcFleet
from tpu_composer.sim.churn import ChurnDriver, generate_plan

from tests.test_crash_restart import assert_no_double_attach
from tests.test_proc_fleet import (
    _cr_states,
    _pending_intents,
    _pool_attach_events,
    _wait,
    _workdir,
)

pytestmark = [pytest.mark.slow, pytest.mark.partition]

LEASE_S = 2.0
RENEW_S = 0.25
#: shards.py default: fence a shard renew_deadline after its last
#: successful renew (lease_duration * 2/3).
RENEW_DEADLINE_S = LEASE_S * 2.0 / 3.0
#: Fence bound: the victim's last successful renew is at most one renew
#: period before the partition, the deadline check runs on the next tick
#: after the wire fails fast (mux ping deadline ~1.25s with the knobs
#: below), and ops already handed to the dispatcher may still execute
#: against the fabric. Everything after this is an UNFENCED mutation.
FENCE_SLACK_S = 2.5
#: Lease takeover: one of the victim's in-flight renews may LAND (s2c:
#: the request applied, the response went dark) and push renewTime
#: forward once before CAS staleness stops the rest.
TAKEOVER_BOUND_S = 2 * LEASE_S + 4 * RENEW_S + 3.0
#: How long the wire stays dark: long enough for takeover plus a quiet
#: window that would expose a late unfenced mutation.
PARTITION_HOLD_S = 9.0

#: Wire knobs for every replica: fast ping deadline (detection within
#: ~1.25s of onset), fast dial timeout so reconnect probes into the
#: accepted-but-dark proxy fail in bounded time, and the default flap
#: streak so the victim exercises mux fail-fast (not an instant HTTP
#: fallback whose blocking reads would wedge the fencing tick).
WIRE_ENV = {
    "TPUC_WIRE_PING_PERIOD": "0.5",
    "TPUC_WIRE_PING_MISSES": "2",
    "TPUC_WIRE_CONNECT_TIMEOUT": "1.0",
    "TPUC_WIRE_MUX_MAX_FAILS": "5",
}


class TestAsymmetricPartitionSoak:
    def test_partitioned_replica_fences_survivors_steal_heal_converges(
            self, tmp_path):
        seed = int(os.environ.get("TPUC_PARTITION_SEED", "20"))
        plan = generate_plan(
            seed=seed,
            requests=18,
            duration_s=6.0,
            nodes=16,
            chips_per_node=4,
            min_size=1,
            max_size=2,
            cancel_frac=0.15,
            resize_frac=0.2,
            migrate_frac=0.0,
        )
        fleet = ProcFleet(
            _workdir(tmp_path, "partition"),
            nodes=plan.nodes,
            chips_per_node=plan.chips_per_node,
            shards=6,
            expected_replicas=3,
            lease_duration_s=LEASE_S,
            lease_renew_s=RENEW_S,
            extra_env=WIRE_ENV,
            netchaos=True,
        )
        with fleet:
            for name in ("part-a", "part-b", "part-c"):
                fleet.spawn(name, wait_ready_s=60)
            _wait(
                lambda: len(fleet.shard_owners()) == fleet.shards
                and len(set(fleet.shard_owners().values())) == 3,
                30,
                "shard leases never balanced across all three replicas",
            )

            driver = ChurnDriver(fleet.apiserver.url, plan, GROUP, VERSION)
            churn = threading.Thread(
                target=driver.run, daemon=True, name="partition-churn")
            churn.start()
            try:
                # Let churn build in-flight state, then pick the busiest
                # replica — most durable intents in shards it owns.
                def busiest():
                    counts = fleet.in_flight_intents()
                    if counts:
                        return max(counts, key=counts.get)
                    return None

                try:
                    victim = _wait(busiest, 10, "no in-flight intents")
                except TimeoutError:
                    victim = "part-a"
                survivors = [r.name for r in fleet.live()
                             if r.name != victim]

                # --- the lie begins: requests land, responses go dark ---
                t_partition = time.monotonic()
                fleet.proxy(victim).partition("s2c")

                # Survivors steal every one of the victim's shards.
                def stolen():
                    owners = fleet.shard_owners()
                    return (len(owners) == fleet.shards
                            and victim not in owners.values())

                _wait(
                    stolen,
                    TAKEOVER_BOUND_S,
                    f"survivors never stole {victim}'s shards:"
                    f" {fleet.shard_owners()}",
                )
                takeover_s = time.monotonic() - t_partition
                assert set(fleet.shard_owners().values()) <= set(survivors)

                # Hold the partition open well past takeover: a victim
                # that keeps mutating the fabric would show itself here.
                remaining = PARTITION_HOLD_S - (time.monotonic() - t_partition)
                if remaining > 0:
                    time.sleep(remaining)

                # Ride-through, not crash: the victim is wedged, not dead.
                assert fleet.replicas[victim].alive(), (
                    f"{victim} died during the partition — outage"
                    " ride-through is the contract:\n"
                    + fleet.tail_log(victim)
                )

                # --- fencing witness (supervisor-side, attributed) ------
                fence_deadline = t_partition + RENEW_DEADLINE_S + FENCE_SLACK_S
                with fleet.fabric._lock:
                    ledger = list(fleet.fabric.mutation_log)
                assert ledger, "fabric ledger recorded no mutations at all"
                late = [(ident, t - t_partition, verb, names)
                        for ident, t, verb, names in ledger
                        if ident == victim and t > fence_deadline]
                assert not late, (
                    f"UNFENCED: {victim} mutated the fabric"
                    f" {late[0][1]:.2f}s after partition onset (deadline"
                    f" {RENEW_DEADLINE_S + FENCE_SLACK_S:.2f}s): {late}"
                )

                # --- heal: the same wire comes back ---------------------
                fleet.proxy(victim).heal()
            finally:
                driver.stop()
                churn.join(timeout=30)

            def converged():
                states = _cr_states(fleet)
                return (states
                        and all(s == "Running" for s in states.values())
                        and _pending_intents(fleet) == 0)

            _wait(
                converged,
                90,
                f"fleet never converged after heal: {_cr_states(fleet)},"
                f" pending={_pending_intents(fleet)}",
            )

            # The victim survived the entire episode as one process.
            assert fleet.replicas[victim].alive()
            assert fleet.replicas[victim].generation == 1

            # Nonce-checked zero double-attach across the partition,
            # the takeover and the heal.
            events = _pool_attach_events(fleet)
            assert events, "pool recorded no materializations"
            assert_no_double_attach(events)

            # Detection evidence for the bench/README claim: takeover is
            # governed by the lease clock, nowhere near a 30s-per-request
            # discovery baseline.
            assert takeover_s < TAKEOVER_BOUND_S

            fleet.stop_all()
