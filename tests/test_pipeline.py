"""Pipeline parallelism: GPipe schedule equals the unpipelined stack."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_composer.models.transformer import ModelConfig, forward, init_params, param_specs
from tpu_composer.parallel import pipeline


def make_model(n_layers=4, seq=16):
    c = ModelConfig(
        vocab_size=128, d_model=32, n_layers=n_layers, n_heads=4, d_ff=64,
        max_seq=seq, dtype=jnp.float32,
    )
    params = init_params(c, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, seq), 0, c.vocab_size)
    return c, params, tokens


def stacked_params(params):
    return {
        "embed": params["embed"],
        "layers": pipeline.stack_layers(params["layers"]),
        "ln_f": params["ln_f"],
    }


def shard_stacked(params, c, mesh):
    layer_spec = param_specs(c)["layers"][0]
    specs = {
        "embed": P(),
        "layers": pipeline.stacked_layer_specs(layer_spec, mesh=mesh),
        "ln_f": P(),
    }
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def test_stack_layers_roundtrip():
    _, params, _ = make_model()
    stacked = pipeline.stack_layers(params["layers"])
    assert stacked["wqkv"].shape[0] == len(params["layers"])
    np.testing.assert_array_equal(
        np.asarray(stacked["w_up"][2]), np.asarray(params["layers"][2]["w_up"])
    )


@pytest.mark.parametrize("n_micro", [1, 2, 4])
def test_pipelined_forward_matches_dense(n_micro):
    c, params, tokens = make_model()
    want = forward(params, tokens, c)

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]).reshape(4), ("pp",))
    sp = shard_stacked(stacked_params(params), c, mesh)
    got = jax.jit(
        lambda p, t: pipeline.pipelined_forward(p, t, c, mesh, n_micro)
    )(sp, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pipeline_with_tp_and_dp_axes():
    """pp manual + dp/tp auto in one mesh: stage einsums keep their GSPMD
    tensor-parallel sharding inside the partial-manual shard_map."""
    c, params, tokens = make_model()
    want = forward(params, tokens, c)

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:8]).reshape(2, 2, 2), ("dp", "pp", "tp"))
    layer_spec = param_specs(c)["layers"][0]
    specs = {
        "embed": P("tp", None),
        "layers": pipeline.stacked_layer_specs(layer_spec),
        "ln_f": P(),
    }
    sp = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        stacked_params(params), specs,
    )
    tok = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))
    got = jax.jit(
        lambda p, t: pipeline.pipelined_forward(p, t, c, mesh, 2)
    )(sp, tok)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)


def test_pipelined_grads_match_dense():
    """Reverse-mode through the scan/ppermute schedule equals dense grads."""
    c, params, tokens = make_model(n_layers=2)
    from tpu_composer.models.transformer import loss_fn

    dense_loss, dense_grads = jax.value_and_grad(loss_fn)(params, tokens, c)

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:2]).reshape(2), ("pp",))
    sp = shard_stacked(stacked_params(params), c, mesh)
    pl_loss, pl_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t: pipeline.pipelined_loss_fn(p, t, c, mesh, 2)
        )
    )(sp, tokens)

    assert abs(float(pl_loss) - float(dense_loss)) < 1e-4
    got = np.asarray(pl_grads["layers"]["wqkv"])  # (L, ...)
    want = np.stack([np.asarray(g["wqkv"]) for g in dense_grads["layers"]])
    np.testing.assert_allclose(got, want, atol=5e-4)
    np.testing.assert_allclose(
        np.asarray(pl_grads["embed"]), np.asarray(dense_grads["embed"]), atol=5e-4
    )


def test_pp1_falls_back_to_plain_stack():
    c, params, tokens = make_model(n_layers=2)
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:1]).reshape(1), ("pp",))
    sp = stacked_params(params)
    got = pipeline.pipelined_forward(sp, tokens, c, mesh, 2)
    want = forward(params, tokens, c)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)
