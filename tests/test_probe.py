"""Staged accelerator probe — the failure path must produce evidence.

BENCH_r01/r02 both died in backend_init with an empty stderr tail (VERDICT r2
weak #1): the probe's entire value is that a wedge yields a named stage, a
thread stack dump, pool-endpoint reachability, and a retry record. These
tests drive the parent driver against scripted children so the diagnosis
machinery is pinned without needing a real hang on real hardware.
"""

from __future__ import annotations

import pytest

import tpu_composer.workload.probe as probe

# A child that completes every stage instantly.
_FAST_CHILD = r"""
import json, time
for stage in ("backend_init", "matmul", "flash_attn", "qualify",
              "qualify_large", "decode"):
    print("STAGE_RESULT " + json.dumps({"stage": stage, "seconds": 0.0, "ok": True}),
          flush=True)
"""

# A child that wedges inside backend_init, with the real child's watchdog.
_WEDGED_CHILD = r"""
import faulthandler, os, time
_budget = float(os.environ.get("TPUC_PROBE_STAGE_BUDGET_S", "480"))
faulthandler.dump_traceback_later(max(_budget - 10.0, 2.0), exit=True)
time.sleep(600)
"""


def test_all_stages_complete(monkeypatch):
    monkeypatch.setattr(probe, "_CHILD", _FAST_CHILD)
    r = probe.staged_accelerator_probe(timeouts={"backend_init": 10.0})
    assert r["completed"] == ["devnodes", "backend_init", "matmul",
                              "flash_attn", "qualify", "qualify_large",
                              "decode"]
    assert "failed_stage" not in r


def test_wedged_backend_init_yields_stack_and_retries(monkeypatch):
    monkeypatch.setattr(probe, "_CHILD", _WEDGED_CHILD)
    # fallbacks=False: the cpu-fallback would just re-wedge the scripted
    # child and the AOT compile path has its own suite — without it this
    # test spent 90+ s of suite wall-clock proving nothing new.
    r = probe.staged_accelerator_probe(timeouts={"backend_init": 8.0},
                                       retries=1, fallbacks=False)
    assert r["failed_stage"] == "backend_init"
    d = r["diagnosis"]
    # One retry happened and each attempt's evidence is kept.
    assert d["attempts"] == 2
    assert len(d["earlier_attempts"]) == 1
    # The in-child faulthandler dump reached the recorded stderr tail —
    # the exact blocking line must be visible.
    assert any("time.sleep" in line or "Thread" in line
               for line in d["stderr_tail"]), d["stderr_tail"]
    # Preflight reachability of the pool/tunnel endpoints is part of the
    # diagnosis (empty list is fine when no pool env is set).
    assert "pool_endpoints" in d


def test_loopback_relay_disarms_tunnel_down_clamp(monkeypatch):
    """r05 incident pin: with AXON_LOOPBACK_RELAY set, an all-refused TCP
    preflight must NOT be read as 'relay provably down' (the loopback relay
    owns no TCP listener) — backend_init keeps its budget and retries."""
    monkeypatch.setattr(probe, "_CHILD", _WEDGED_CHILD)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    r = probe.staged_accelerator_probe(timeouts={"backend_init": 6.0},
                                       retries=1, fallbacks=False)
    d = r["diagnosis"]
    assert d["tunnel_down"] is False
    assert d["attempts"] == 2  # retries NOT zeroed by the clamp

    # Control: same dead endpoints without loopback mode → clamp fires.
    monkeypatch.delenv("AXON_LOOPBACK_RELAY")
    r2 = probe.staged_accelerator_probe(timeouts={"backend_init": 6.0},
                                        retries=1, fallbacks=False)
    d2 = r2["diagnosis"]
    assert d2["tunnel_down"] is True
    assert d2["attempts"] == 1


def test_loopback_mode_caps_handshake_budget(monkeypatch):
    """Loopback mode keeps retries but bounds backend_init (~15× a healthy
    handshake): a wedged in-process relay must cost minutes per probe, not
    480 s × attempts — the end-of-round bench runs on this path. Driven
    with an instant-fail child: diagnosis.timeout_s records the budget the
    parent computed for the stage."""
    monkeypatch.setattr(probe, "_CHILD", "import sys; sys.exit(1)")
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    r = probe.staged_accelerator_probe(retries=0, fallbacks=False)
    d = r["diagnosis"]
    assert r["failed_stage"] == "backend_init"
    assert d["tunnel_down"] is False
    assert d["timeout_s"] == 150.0  # capped from the 480 s default
    # An explicit smaller caller budget still wins over the cap.
    r2 = probe.staged_accelerator_probe(timeouts={"backend_init": 5.0},
                                        retries=0, fallbacks=False)
    assert r2["diagnosis"]["timeout_s"] == 5.0


def test_loopback_relay_mode_spellings():
    on = {"AXON_LOOPBACK_RELAY": "1"}
    assert probe.loopback_relay_mode(on) is True
    assert probe.loopback_relay_mode({"AXON_LOOPBACK_RELAY": "true"}) is True
    # Conventional opt-out spellings must read as OFF — string truthiness
    # would treat the explicit AXON_LOOPBACK_RELAY=0 as loopback mode.
    for off in ("", "0", "false", "no", "off", " 0 "):
        assert probe.loopback_relay_mode({"AXON_LOOPBACK_RELAY": off}) is False
    assert probe.loopback_relay_mode({}) is False


def test_pool_endpoint_parsing(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1:1, 198.51.100.7:80")
    monkeypatch.delenv("AXON_POOL_SVC_OVERRIDE", raising=False)
    recs = probe.probe_pool_endpoints(timeout_s=0.2)
    eps = {r["endpoint"] for r in recs}
    # Explicit host:port entries are used verbatim (no port guessing).
    assert eps == {"127.0.0.1:1", "198.51.100.7:80"}
    # Port 1 on loopback is closed: must report unreachable, not raise.
    rec = next(r for r in recs if r["endpoint"] == "127.0.0.1:1")
    assert rec["reachable"] is False and "error" in rec


def test_bare_host_scans_candidate_ports(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.delenv("AXON_POOL_SVC_OVERRIDE", raising=False)
    recs = probe.probe_pool_endpoints(timeout_s=0.2)
    assert len(recs) == 4  # the relay's known candidate ports
    assert all(r["endpoint"].startswith("127.0.0.1:") for r in recs)
