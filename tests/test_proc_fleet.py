"""Process-mode fleet soak (ISSUE 17 headline tests).

ProcFleet spawns FULL operator replicas as real OS processes (the exact
``python -m tpu_composer --shards K`` cmd/main wiring) against the served
sim apiserver and a served fake fabric, then proves the cross-process
robustness contract that the in-proc shard soaks could only approximate:

- kill -9 failover across REAL pids: the replica owning the most in-flight
  durable intents is SIGKILLed mid-attach-wave; survivors CAS-steal its
  shard leases and converge every request to Running,
- the nonce-checked zero-double-attach invariant holds across the handoff,
  witnessed supervisor-side from the shared pool's event ring (every
  materialization carries its intent nonce; an idempotent re-attach emits
  nothing),
- the failover renders as ONE stitched trace across two real processes:
  the victim's pre-kill /debug/traces snapshot (SIGKILL skips its atexit
  dump) merged with the survivors' TPUC_TRACE_FILE dumps yields a span
  under the victim's stable replica pid and an adopt span under a
  survivor's, joined by a synthetic flow arrow — extending the
  test_shard_failover discipline from threads to processes,
- named-process discipline: the merged document carries process_name
  metadata mapping each stable replica pid to its --replica-id.

A second scenario is the CI proc-smoke: a seeded 2-process mini-churn
(arrivals, cancels, resizes from sim/churn.py) that must converge with
zero pending intents inside a bounded wall time, leaving per-replica
artifacts (log, flight, trace, fleet view) for upload on failure.

Run: ``make proc-smoke`` (markers slow+proc).
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tpu_composer import GROUP, VERSION
from tpu_composer.fleet.proc import ProcFleet
from tpu_composer.runtime import tracing
from tpu_composer.sim.churn import ChurnDriver, generate_plan, simulate

from tests.test_crash_restart import assert_no_double_attach

pytestmark = [pytest.mark.slow, pytest.mark.proc]

GV = f"{GROUP}/{VERSION}"
LEASE_S = 2.0
RENEW_S = 0.25
# Observation-clock lease expiry + detection granularity + scheduling
# slack — same shape as test_shard_failover's bound, plus real-process
# startup noise.
TAKEOVER_BOUND_S = LEASE_S + 4 * RENEW_S + 1.0


def _workdir(tmp_path, leaf: str) -> str:
    """Fleet workdir: tmp_path locally; under $TPUC_PROC_WORKDIR when CI
    sets it, so the per-replica black boxes (flight/trace/fleet/port/log
    per pid) survive the run and upload as failure artifacts."""
    base = os.environ.get("TPUC_PROC_WORKDIR")
    if base:
        path = os.path.join(base, leaf)
        os.makedirs(path, exist_ok=True)
        return path
    return str(tmp_path / leaf)


def _wait(pred, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = pred()
        if value:
            return value
        time.sleep(0.05)
    raise TimeoutError(what)


def _cr_doc(name: str, size: int) -> dict:
    return {
        "apiVersion": GV,
        "kind": "ComposabilityRequest",
        "metadata": {"name": name},
        "spec": {"resource": {"type": "tpu", "model": "tpu-v4", "size": size}},
    }


def _cr_states(fleet):
    with fleet.apiserver.state.lock:
        return {
            lname: ((obj.get("status") or {}).get("state"))
            for (prefix, lname), obj in fleet.apiserver.state.objects.items()
            if prefix == fleet.cr_prefix
        }


def _pending_intents(fleet) -> int:
    with fleet.apiserver.state.lock:
        return sum(
            1
            for (prefix, _), obj in fleet.apiserver.state.objects.items()
            if prefix == fleet.res_prefix
            and (obj.get("status") or {}).get("pending_op")
        )


def _pool_attach_events(fleet):
    """Map the shared pool's op_completed ring into the
    (attach, name, nonce) / (release, name) tuples the crash-restart
    witness checks. Emission happens only on true materialization
    (inmem.py returns early on idempotent re-attach), so a double-attach
    across ANY pair of processes shows up here."""
    raw, _cursor = fleet.pool.poll_events(0, timeout=0)
    events = []
    for e in raw:
        if e.type != "op_completed" or e.outcome != "ok":
            continue
        if e.verb == "add":
            events.append(("attach", e.resource, e.nonce))
        elif e.verb == "remove":
            events.append(("release", e.resource))
    return events


class TestProcKill9Failover:
    def test_kill9_failover_converges_without_double_attach(self, tmp_path):
        fleet = ProcFleet(
            _workdir(tmp_path, "failover"),
            nodes=8,
            chips_per_node=4,
            shards=8,
            expected_replicas=2,
            lease_duration_s=LEASE_S,
            lease_renew_s=RENEW_S,
        )
        with fleet:
            fleet.spawn("alpha", wait_ready_s=60)
            fleet.spawn("beta", wait_ready_s=60)
            _wait(
                lambda: len(fleet.shard_owners()) == fleet.shards
                and len(set(fleet.shard_owners().values())) == 2,
                30,
                "shard leases never balanced across both replicas",
            )

            total = 12
            for i in range(total):
                fleet.apiserver.put_object(
                    fleet.cr_prefix, _cr_doc(f"wave-{i:02d}", 2)
                )

            # Victim = the replica owning the most in-flight durable
            # intents (the ISSUE's victim metric). Degrade gracefully to
            # any live replica if the wave already drained — the kill is
            # still mid-lifecycle for whatever remains.
            def pick_victim():
                counts = fleet.in_flight_intents()
                if counts:
                    return max(counts, key=counts.get)
                return None

            try:
                victim = _wait(pick_victim, 15, "no in-flight intents seen")
            except TimeoutError:
                victim = fleet.live()[0].name
            survivors = [r.name for r in fleet.live() if r.name != victim]
            fleet.kill(victim)  # snapshots /debug/traces, then SIGKILL
            assert not fleet.replicas[victim].alive()

            def all_running():
                states = _cr_states(fleet)
                return len(states) == total and all(
                    s == "Running" for s in states.values()
                )

            _wait(
                all_running,
                TAKEOVER_BOUND_S + 30,
                f"wave never converged after kill -9 of {victim}:"
                f" {_cr_states(fleet)}",
            )
            _wait(
                lambda: _pending_intents(fleet) == 0,
                30,
                "durable intents never drained after failover",
            )

            # Survivors own every shard; the dead identity holds none.
            owners = fleet.shard_owners()
            assert len(owners) == fleet.shards
            assert victim not in owners.values()
            assert set(owners.values()) <= set(survivors)

            # Nonce-checked zero double-attach across two real pids.
            events = _pool_attach_events(fleet)
            assert events, "pool recorded no materializations"
            assert_no_double_attach(events)

            # Graceful stop dumps the survivors' TPUC_TRACE_FILEs; the
            # victim's half is its pre-kill snapshot.
            fleet.stop_all()
            assert "trace_prekill" in fleet.replicas[victim].artifacts
            merged = fleet.merged_trace()
            self._assert_failover_stitches(merged, victim)

    def _assert_failover_stitches(self, merged, victim):
        """test_shard_failover's ISSUE-12 discipline, applied to a merge
        of REAL per-process trace files: some intent nonce must render as
        a span under the victim's stable replica pid and an adopt span
        under a survivor's, connected by a stitched flow arrow."""
        victim_pid = tracing.replica_pid(victim)
        merged_path = os.environ.get("TPUC_MERGED_TRACE_FILE")
        if merged_path:  # CI failure artifact (written on success too)
            with open(merged_path, "w") as f:
                json.dump(merged, f)

        # Named-process discipline: every replica pid present in the
        # merge is labeled with its --replica-id.
        names = {
            e["pid"]: e["args"]["name"]
            for e in merged["traceEvents"]
            if e.get("name") == "process_name"
        }
        assert names.get(victim_pid) == victim, (
            f"victim pid {victim_pid} not named {victim!r}: {names}"
        )

        spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        by_trace = {}
        for e in spans:
            trace_id = (e.get("args") or {}).get("trace_id")
            if trace_id:
                by_trace.setdefault(trace_id, []).append(e)
        stitched = [
            e
            for e in merged["traceEvents"]
            if e.get("ph") in ("s", "f") and e["args"].get("stitched")
        ]
        connected = []
        for trace_id, evs in by_trace.items():
            pids = {e["pid"] for e in evs}
            if victim_pid not in pids or len(pids) < 2:
                continue
            if not any(
                e["name"] == "adopt" and e["pid"] != victim_pid for e in evs
            ):
                continue
            if any(f["args"]["trace_id"] == trace_id for f in stitched):
                connected.append(trace_id)
        summary = sorted(
            (t, sorted({e["pid"] for e in evs}))
            for t, evs in by_trace.items()
        )[:10]
        assert connected, (
            "no intent nonce rendered as one connected flow across the"
            " victim's and a survivor's real-process trace files —"
            f" traces: {summary}"
        )


class TestProcMiniChurnSmoke:
    def test_two_process_mini_churn_converges(self, tmp_path):
        """CI proc-smoke: seeded open-loop mini-churn against a 2-process
        fleet must converge (every surviving request Running, zero
        pending intents) inside a bounded wall time."""
        seed = int(os.environ.get("TPUC_PROC_SMOKE_SEED", "17"))
        plan = generate_plan(
            seed=seed,
            requests=24,
            duration_s=4.0,
            nodes=16,
            chips_per_node=4,
            min_size=1,
            max_size=2,
            cancel_frac=0.2,
            resize_frac=0.2,
            migrate_frac=0.0,
        )
        model = simulate(plan)  # deterministic reference for the plan
        fleet = ProcFleet(
            _workdir(tmp_path, "churn"),
            nodes=plan.nodes,
            chips_per_node=plan.chips_per_node,
            shards=8,
            expected_replicas=2,
            lease_duration_s=LEASE_S,
            lease_renew_s=RENEW_S,
        )
        with fleet:
            fleet.spawn("smoke-a", wait_ready_s=60)
            fleet.spawn("smoke-b", wait_ready_s=60)
            _wait(
                lambda: len(fleet.shard_owners()) == fleet.shards,
                30,
                "shard leases never fully claimed",
            )
            driver = ChurnDriver(fleet.apiserver.url, plan, GROUP, VERSION)
            try:
                driver.run()

                def converged():
                    states = _cr_states(fleet)
                    return (
                        states
                        and all(s == "Running" for s in states.values())
                        and _pending_intents(fleet) == 0
                    )

                _wait(
                    converged,
                    60,
                    f"mini-churn never converged: {_cr_states(fleet)},"
                    f" pending={_pending_intents(fleet)}",
                )
            finally:
                driver.stop()

            states = _cr_states(fleet)
            # Max concurrent demand fits inventory, so every surviving
            # arrival must place — the count can't exceed the model's
            # arrivals and must cover everything not cancelled pre-place.
            assert len(states) <= model["arrivals"]
            cancels = plan.counts().get("cancel", 0)
            assert len(states) >= model["arrivals"] - cancels, (
                f"too few survivors: {len(states)} of {model['arrivals']}"
            )
            assert_no_double_attach(_pool_attach_events(fleet))

            # Per-replica artifact discipline: each replica left its
            # flight/trace/fleet/log files for CI collection.
            fleet.stop_all()
            for name, arts in fleet.artifact_index().items():
                assert os.path.exists(arts["log"]), name
                assert os.path.exists(arts["trace"]), name
