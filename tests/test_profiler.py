"""Control-plane observatory: sampling profiler, lock-contention
telemetry, and the debug endpoints that serve them.

The load test is the ISSUE 11 acceptance spine for the profiler half: the
always-on sampler runs across a 32-chip attach wave without wedging it,
and every named subsystem thread that exists in the harness shows up in
the attribution (a thread landing in 'other' means a naming regression
the profiler would silently misattribute forever).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposableResource,
    ComposableResourceSpec,
    Node,
    ObjectMeta,
)
from tpu_composer.controllers import (
    ComposableResourceReconciler,
    ResourceTiming,
    UpstreamSyncer,
)
from tpu_composer.fabric.dispatcher import FabricDispatcher
from tpu_composer.fabric.events import FabricSession
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime import contention, profiler
from tpu_composer.runtime.contention import BusyTracker, ObservedLock
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.metrics import (
    lock_hold_seconds,
    lock_wait_seconds,
    queue_wait_seconds,
    worker_busy_ratio,
)
from tpu_composer.runtime.profiler import (
    SamplingProfiler,
    profile_burst,
    subsystem_for,
)
from tpu_composer.runtime.queue import RateLimitingQueue
from tpu_composer.runtime.store import Store


# ---------------------------------------------------------------------------
# subsystem attribution
# ---------------------------------------------------------------------------

class TestSubsystemAttribution:
    @pytest.mark.parametrize("name,expect", [
        ("fabric-dispatch-3", "dispatcher-lane"),
        ("ComposableResourceReconciler-worker-0", "reconcile-worker"),
        ("ComposabilityRequestReconciler-dispatch-Node", "watch-dispatch"),
        ("UpstreamSyncer", "syncer"),
        ("lease-renew", "elector"),
        ("shard-lease-renew", "elector"),
        ("fabric-events-fabric", "session"),
        ("FabricSession", "session"),
        ("informer-ComposableResource", "informer"),
        ("kubecache-Node", "informer"),
        ("lifecycle-watch", "lifecycle"),
        ("health", "http"),
        ("profiler", "observatory"),
        ("slo-engine", "observatory"),
        ("MainThread", "main"),
        ("Thread-4 (process_request_thread)", "http"),
        ("Thread-17", "other"),
    ])
    def test_names_map_to_stable_buckets(self, name, expect):
        assert subsystem_for(name) == expect


# ---------------------------------------------------------------------------
# ObservedLock: wait/hold accounting, reentrancy, Condition parks
# ---------------------------------------------------------------------------

class TestObservedLock:
    def test_wait_and_hold_observed_once_per_outermost_pair(self):
        lk = ObservedLock("t_ol_reent", reentrant=True)
        holds0 = lock_hold_seconds.count(lock="t_ol_reent")
        waits0 = lock_wait_seconds.count(lock="t_ol_reent")
        with lk:
            with lk:  # inner re-acquire: free
                pass
        assert lock_hold_seconds.count(lock="t_ol_reent") == holds0 + 1
        assert lock_wait_seconds.count(lock="t_ol_reent") == waits0 + 1

    def test_contended_acquire_records_the_wait(self):
        lk = ObservedLock("t_ol_contend")
        release = threading.Event()
        held = threading.Event()

        def holder():
            with lk:
                held.set()
                release.wait(2.0)

        t = threading.Thread(target=holder)
        t.start()
        held.wait(2.0)
        t0 = time.perf_counter()
        threading.Timer(0.05, release.set).start()
        with lk:
            waited = time.perf_counter() - t0
        t.join()
        assert waited >= 0.04
        p100 = lock_wait_seconds.percentile(1.0, lock="t_ol_contend")
        assert p100 is not None and p100 >= 0.04

    def test_condition_park_is_not_wait_or_hold(self):
        # The regression this wrapper must never reintroduce: a worker
        # parked in cond.wait() for 300 ms must not record a 300 ms lock
        # wait OR a 300 ms hold — the lock is released while parked.
        lk = ObservedLock("t_ol_park", reentrant=True)
        cond = threading.Condition(lk)

        def parker():
            with cond:
                cond.wait(timeout=0.3)

        t = threading.Thread(target=parker)
        t.start()
        t.join()
        for hist in (lock_wait_seconds, lock_hold_seconds):
            worst = hist.percentile(1.0, lock="t_ol_park")
            assert worst is not None and worst < 0.25, (hist.name, worst)

    def test_disabled_mode_observes_nothing_but_still_locks(self):
        contention.set_enabled(False)
        try:
            lk = ObservedLock("t_ol_off")
            with lk:
                pass
            assert lock_hold_seconds.count(lock="t_ol_off") == 0
            assert lock_wait_seconds.count(lock="t_ol_off") == 0
            # Mutual exclusion still real.
            assert lk.acquire(blocking=False) is True
            lk.release()
        finally:
            contention.set_enabled(True)

    def test_busy_tracker_sets_the_gauge_after_a_window(self):
        tr = BusyTracker("t_pool", workers=2, window=0.01)
        tr.add(0.02)
        time.sleep(0.02)
        tr.add(0.02)
        ratio = worker_busy_ratio.value(pool="t_pool")
        assert 0.0 < ratio <= 1.0


class TestQueueWait:
    def test_enqueue_to_dequeue_wait_is_observed(self):
        q = RateLimitingQueue(name="t_queue_wait")
        before = queue_wait_seconds.count(queue="t_queue_wait")
        q.add("k1")
        time.sleep(0.03)
        assert q.get(timeout=1.0) == "k1"
        assert queue_wait_seconds.count(queue="t_queue_wait") == before + 1
        worst = queue_wait_seconds.percentile(1.0, queue="t_queue_wait")
        assert worst is not None and worst >= 0.02

    def test_delayed_entries_time_from_promotion_not_add_after(self):
        # add_after is an intentional delay (a poll timer), not
        # saturation: the wait clock must start when the key becomes
        # READY, so the observed wait is ~0, not ~the delay.
        q = RateLimitingQueue(name="t_queue_delay")
        q.add_after("k1", 0.1)
        assert q.get(timeout=2.0) == "k1"
        worst = queue_wait_seconds.percentile(1.0, queue="t_queue_delay")
        assert worst is not None and worst < 0.09


# ---------------------------------------------------------------------------
# sampler mechanics
# ---------------------------------------------------------------------------

class TestSampler:
    def test_burst_catches_a_busy_thread_with_cpu_split(self):
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(i * i for i in range(500))

        t = threading.Thread(target=spin, name="CRR-worker-0")
        t.start()
        try:
            prof = profile_burst(seconds=0.25, interval=0.005)
        finally:
            stop.set()
            t.join()
        summary = prof.thread_summary()
        assert "reconcile-worker" in summary
        rw = summary["reconcile-worker"]
        assert rw["samples"] > 0
        assert rw["blocked_samples"] < rw["samples"]  # it was spinning
        top = prof.top(5)
        assert any("spin" in f["frame"] or "genexpr" in f["frame"] for f in top)
        collapsed = prof.collapsed()
        assert collapsed  # "sub;frame;frame N" lines
        line = collapsed.splitlines()[0]
        stack_part, count = line.rsplit(" ", 1)
        assert int(count) > 0 and ";" in stack_part

    def test_window_ring_is_bounded(self):
        prof = SamplingProfiler(interval=0.001, window_s=0.001, ring=3)
        prof._own_ident = -1  # sample every thread incl. this one
        for _ in range(30):
            prof.sample_once()
            time.sleep(0.002)
        assert len(prof.windows()) <= 4  # ring(3) + the open window

    def test_dump_file_writes_the_ring(self, tmp_path, monkeypatch):
        prof = SamplingProfiler(interval=0.005)
        prof._own_ident = -1
        for _ in range(3):
            prof.sample_once()
        monkeypatch.setattr(profiler, "_active", prof)
        out = tmp_path / "profile.json"
        assert profiler.dump_file(str(out)) == str(out)
        doc = json.loads(out.read_text())
        assert "summary" in doc and doc["interval_s"] == 0.005


# ---------------------------------------------------------------------------
# the acceptance spine: sampler across a 32-chip wave + debug endpoints
# ---------------------------------------------------------------------------

def _wave_world(children=32):
    store = Store()
    n = Node(metadata=ObjectMeta(name="wave-node"))
    n.status.tpu_slots = children
    store.create(n)
    pool = InMemoryPool(chips={"gpu-a100": children})
    agent = FakeNodeAgent(pool=pool)
    dispatcher = FabricDispatcher(pool, batch_window=0.02, poll_interval=0.01,
                                  concurrency=8)
    session = FabricSession(pool, poll_timeout=0.5, retry_base=0.01)
    dispatcher.attach_session(session)
    mgr = Manager(
        store=store, health_addr="127.0.0.1:0",
        profiler=SamplingProfiler(interval=0.005, window_s=0.25),
    )
    mgr.add_controller(ComposableResourceReconciler(
        store, pool, agent, dispatcher=dispatcher,
        timing=ResourceTiming(attach_poll=0.01, visibility_poll=0.01,
                              detach_poll=0.01, detach_fast=0.01,
                              busy_poll=0.01)))
    mgr.add_runnable(dispatcher.run)
    mgr.add_runnable(session.run)
    mgr.add_runnable(UpstreamSyncer(store, pool, period=0.1))
    return store, pool, dispatcher, mgr


class TestProfilerUnderLoad:
    def test_wave_converges_with_sampler_on_and_all_subsystems_attributed(self):
        store, pool, dispatcher, mgr = _wave_world()
        mgr.start(workers_per_controller=4)
        try:
            names = [f"w-{i}" for i in range(32)]
            for name in names:
                store.create(ComposableResource(
                    metadata=ObjectMeta(name=name),
                    spec=ComposableResourceSpec(
                        type="gpu", model="gpu-a100",
                        target_node="wave-node"),
                ))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(
                    (r := store.try_get(ComposableResource, n2)) is not None
                    and r.status.state == "Online" for n2 in names
                ):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("32-chip wave never attached with sampler on")
            time.sleep(0.3)  # let at least one window roll
            summary = mgr.profiler.thread_summary()
            # Every named subsystem thread that exists in this harness
            # must be attributed — none may fall into 'other'.
            for sub in ("reconcile-worker", "dispatcher-lane", "syncer",
                        "session", "watch-dispatch", "lifecycle"):
                assert sub in summary, (sub, sorted(summary))
            # GIL/wall split present and sane on the busiest subsystem.
            rw = summary["reconcile-worker"]
            assert rw["wall_s"] > 0
            assert rw["gil_wait_s"] >= 0.0
            assert mgr.profiler.collapsed(), "no collapsed stacks collected"
        finally:
            mgr.stop()
            dispatcher.stop()

    def test_debug_endpoints_serve_the_observatory(self):
        store, pool, dispatcher, mgr = _wave_world(children=4)
        mgr.start(workers_per_controller=2)
        try:
            port = mgr.health_port

            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=30
                ) as resp:
                    return resp.read().decode()

            idx = json.loads(get("/debug"))
            for route in ("/debug/slo", "/debug/profile",
                          "/debug/profile/continuous", "/debug/traces"):
                assert route in idx["endpoints"], route
            slo_doc = json.loads(get("/debug/slo"))
            assert set(slo_doc["objectives"]) == {
                "attach_p99", "completion_p50", "queue_wait_p99",
                "repair_p99",
            }
            time.sleep(0.3)
            cont = json.loads(get("/debug/profile/continuous"))
            assert cont["windows"], "continuous ring empty"
            burst = json.loads(get("/debug/profile?seconds=0.2"))
            assert burst["threads"]
            folded = get("/debug/profile?seconds=0.2&format=collapsed")
            assert all(
                line.rsplit(" ", 1)[1].isdigit()
                for line in folded.splitlines() if line
            )
        finally:
            mgr.stop()
            dispatcher.stop()

    def test_profile_disabled_constructs_no_observatory(self):
        prev = profiler.enabled()
        profiler.set_enabled(False)
        try:
            mgr = Manager(store=Store())
            assert mgr.profiler is None
            assert mgr.slo_engine is None
        finally:
            profiler.set_enabled(prev)
