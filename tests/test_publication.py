"""Scheduler-visible publication + quarantine (VERDICT r1 #3).

Acceptance from the verdict: a test (fake kubelet socket is fine) proving a
pod can claim a composed chip and that detach quarantine blocks new claims.

Three layers here:

1. ``TPUDevicePlugin`` speaking the real kubelet device-plugin gRPC wire
   protocol against a fake kubelet (Registration service on a unix socket;
   kubelet dials back for ListAndWatch/Allocate) — reference parity for the
   DEVICE_PLUGIN path (composableresource_controller.go:252-270).
2. ``DevicePublisher`` ResourceSlice/DeviceTaintRule objects — the DRA path
   (gpus.go:207-239 scan, :894-975 quarantine).
3. The live operator: attach publishes, a scheduler-sim claims a chip,
   delete quarantines mid-detach so new claims are blocked, teardown
   retracts everything.
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time

import grpc
import pytest

from tpu_composer.agent import deviceplugin_pb2 as pb
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.plugin import (
    API_VERSION,
    RESOURCE_NAME,
    TPUDevicePlugin,
)
from tpu_composer.agent.publisher import DevicePublisher, slice_object_name
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.dra import DeviceTaintRule, ResourceSlice
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    RequestTiming,
    ResourceTiming,
)
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.manager import Manager


def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


class FakeKubelet:
    """The kubelet side of the device-plugin contract: serves Registration,
    dials back to registered plugins, consumes ListAndWatch, can Allocate."""

    def __init__(self, plugin_dir: str) -> None:
        self.plugin_dir = plugin_dir
        self.registered = {}  # resource_name -> endpoint
        self.devices = {}  # resource_name -> [(id, health)]
        self._server = None
        self._watch_threads = []
        self._lock = threading.Lock()

    # Registration service -------------------------------------------------
    def _register(self, request: pb.RegisterRequest, context) -> pb.Empty:
        with self._lock:
            self.registered[request.resource_name] = request.endpoint
        t = threading.Thread(
            target=self._consume_list_and_watch,
            args=(request.resource_name, request.endpoint),
            daemon=True,
        )
        t.start()
        self._watch_threads.append(t)
        return pb.Empty()

    def _consume_list_and_watch(self, resource: str, endpoint: str) -> None:
        sock = os.path.join(self.plugin_dir, endpoint)
        channel = grpc.insecure_channel(f"unix:{sock}")
        stream = channel.unary_stream(
            f"/{API_VERSION}.DevicePlugin/ListAndWatch",
            request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.ListAndWatchResponse.FromString,
        )
        try:
            for resp in stream(pb.Empty()):
                with self._lock:
                    self.devices[resource] = [
                        (d.ID, d.health) for d in resp.devices
                    ]
        except grpc.RpcError:
            pass

    def allocate(self, resource: str, device_ids):
        """What the kubelet does when a pod requesting the resource lands."""
        endpoint = self.registered[resource]
        sock = os.path.join(self.plugin_dir, endpoint)
        with grpc.insecure_channel(f"unix:{sock}") as channel:
            allocate = channel.unary_unary(
                f"/{API_VERSION}.DevicePlugin/Allocate",
                request_serializer=pb.AllocateRequest.SerializeToString,
                response_deserializer=pb.AllocateResponse.FromString,
            )
            return allocate(
                pb.AllocateRequest(
                    container_requests=[
                        pb.ContainerAllocateRequest(devices_ids=list(device_ids))
                    ]
                ),
                timeout=5.0,
            )

    def start(self) -> None:
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=4)
        )
        handlers = {
            "Register": grpc.unary_unary_rpc_method_handler(
                self._register,
                request_deserializer=pb.RegisterRequest.FromString,
                response_serializer=pb.Empty.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(
                f"{API_VERSION}.Registration", handlers),)
        )
        os.makedirs(self.plugin_dir, exist_ok=True)
        self._server.add_insecure_port(
            f"unix:{os.path.join(self.plugin_dir, 'kubelet.sock')}"
        )
        self._server.start()

    def stop(self) -> None:
        if self._server:
            self._server.stop(grace=0.5)


class TestDevicePluginWire:
    """Real gRPC over unix sockets, both directions."""

    @pytest.fixture()
    def plugin_env(self, tmp_path):
        plugin_dir = str(tmp_path / "device-plugins")
        kubelet = FakeKubelet(plugin_dir)
        kubelet.start()
        devices = {}  # group -> [(id, healthy, dev, cdi)]

        def list_devices():
            return [d for group in sorted(devices) for d in devices[group]]

        plugin = TPUDevicePlugin(list_devices, plugin_dir, node_name="worker-0")
        plugin.start()
        plugin.register_with_kubelet()
        yield kubelet, plugin, devices
        plugin.stop()
        kubelet.stop()

    def test_pod_claims_composed_chip(self, plugin_env):
        kubelet, plugin, devices = plugin_env
        assert wait_for(lambda: RESOURCE_NAME in kubelet.registered, timeout=5)
        # initially nothing composed -> nothing advertised
        assert wait_for(lambda: kubelet.devices.get(RESOURCE_NAME) == [],
                        timeout=5)

        # operator composes a 2-chip group -> plugin pushes the update
        devices["slice-a-worker0"] = [
            ("slice-a-worker0/0", True, "/dev/accel0",
             "tpu.composer.dev/chip=slice-a-worker0"),
            ("slice-a-worker0/1", True, "/dev/accel1",
             "tpu.composer.dev/chip=slice-a-worker0"),
        ]
        plugin.notify()
        assert wait_for(
            lambda: len(kubelet.devices.get(RESOURCE_NAME, [])) == 2, timeout=5
        ), f"kubelet never saw the chips: {kubelet.devices}"

        # pod claims one chip
        resp = kubelet.allocate(RESOURCE_NAME, ["slice-a-worker0/0"])
        cresp = resp.container_responses[0]
        assert cresp.envs["TPU_VISIBLE_CHIPS"] == "slice-a-worker0/0"
        assert cresp.devices[0].host_path == "/dev/accel0"
        assert cresp.cdi_devices[0].name == "tpu.composer.dev/chip=slice-a-worker0"

        # detach retracts -> kubelet sees zero again
        devices.clear()
        plugin.notify()
        assert wait_for(
            lambda: kubelet.devices.get(RESOURCE_NAME) == [], timeout=5
        )

    def test_allocate_unknown_device_fails(self, plugin_env):
        kubelet, plugin, devices = plugin_env
        assert wait_for(lambda: RESOURCE_NAME in kubelet.registered, timeout=5)
        with pytest.raises(grpc.RpcError) as ei:
            kubelet.allocate(RESOURCE_NAME, ["ghost/0"])
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND


class TestPublisherDra:
    def test_publish_claim_quarantine_retract(self, store):
        pub = DevicePublisher(store)
        pub.publish_group("worker-0", "grp-a", ["uuid-1", "uuid-2"], "tpu-v4")
        pub.publish_group("worker-0", "grp-b", ["uuid-3"], "tpu-v4")

        # the slice advertises all three; a scheduler could claim any
        claimable = {d.uuid for d in pub.claimable("worker-0")}
        assert claimable == {"uuid-1", "uuid-2", "uuid-3"}
        assert pub.devices_visible("worker-0", ["uuid-1", "uuid-2"])

        # quarantine grp-a during detach: its chips stop being claimable
        pub.create_taints("worker-0", ["uuid-1", "uuid-2"], "detaching")
        claimable = {d.uuid for d in pub.claimable("worker-0")}
        assert claimable == {"uuid-3"}, "taint did not block claims"

        # retract grp-a: devices leave the slice; untaint
        pub.retract_group("worker-0", "grp-a")
        pub.delete_taints(["uuid-1", "uuid-2"])
        assert pub.devices_invisible("worker-0", ["uuid-1", "uuid-2"])
        assert {d.uuid for d in pub.claimable("worker-0")} == {"uuid-3"}

        # retracting the last group deletes the slice object
        pub.retract_group("worker-0", "grp-b")
        assert store.try_get(ResourceSlice, slice_object_name("worker-0")) is None


class TestOperatorPublishes:
    """End to end: attach publishes, detach quarantines then retracts."""

    @pytest.fixture()
    def operator(self, store):
        for i in range(2):
            n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
            n.status.tpu_slots = 4
            store.create(n)
        pool = InMemoryPool()
        agent = FakeNodeAgent(pool=pool)
        mgr = Manager(store=store)
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool, timing=RequestTiming(updating_poll=0.05,
                                              cleaning_poll=0.05)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, agent,
            timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.05,
                                  detach_poll=0.05, detach_fast=0.05,
                                  busy_poll=0.05)))
        mgr.start(workers_per_controller=2)
        yield store, pool, mgr
        mgr.stop()

    def test_attach_publishes_detach_retracts(self, operator):
        store, pool, mgr = operator
        pub = DevicePublisher(store)
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="r1"),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="tpu", model="tpu-v4", size=8)),
        ))
        assert wait_for(
            lambda: store.get(ComposabilityRequest, "r1").status.state == "Running"
        )
        slices = store.list(ResourceSlice)
        assert slices, "no ResourceSlice published after attach"
        all_uuids = [d.uuid for s in slices for d in s.spec.devices]
        assert len(all_uuids) == 8, f"expected 8 chips published, got {all_uuids}"
        # scheduler-sim: every published chip is claimable pre-detach
        for s in slices:
            node = s.spec.node_name
            assert {d.uuid for d in pub.claimable(node)} == {
                d.uuid for d in s.spec.devices
            }

        store.delete(ComposabilityRequest, "r1")
        assert wait_for(
            lambda: store.try_get(ComposabilityRequest, "r1") is None, timeout=15
        )
        assert store.list(ResourceSlice) == [], "slices not retracted"
        assert store.list(DeviceTaintRule) == [], "taint rules left behind"
