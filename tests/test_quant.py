"""Weight-only int8 quantization for serving (models/quant.py): per-output-
channel scales, transparent resolve() at every weight-use site, combined
with the int8 KV cache for the full quantized-decode path."""

import jax
import jax.numpy as jnp

from tpu_composer.models.decode import generate, prefill
from tpu_composer.models.moe import MoEConfig
from tpu_composer.models.moe import init_params as moe_init
from tpu_composer.models.quant import (
    QTensor,
    embedding_lookup,
    quantize_decode_params,
    quantize_weight,
    resolve,
)
from tpu_composer.models.transformer import (
    ModelConfig,
    forward,
    init_params,
)


def _cfg(**kw):
    base = dict(vocab_size=128, d_model=128, n_layers=2, n_heads=8,
                n_kv_heads=2, d_ff=192, max_seq=64, dtype=jnp.float32)
    base.update(kw)
    return ModelConfig(**base)


class TestQuantizeWeight:
    def test_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.key(0), (64, 3, 8, 16))
        qt = quantize_weight(w, (0,))
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (1, 3, 8, 16)
        deq = resolve(qt, jnp.float32)
        # Per-channel symmetric int8: error <= scale/2 = absmax/254.
        per_chan_max = jnp.max(jnp.abs(w), axis=0, keepdims=True)
        assert bool((jnp.abs(deq - w) <= per_chan_max / 127.0).all())

    def test_resolve_identity_for_arrays(self):
        w = jnp.ones((4, 4), jnp.bfloat16)
        assert resolve(w, jnp.bfloat16) is w

    def test_embedding_lookup_quantized(self):
        embed = jax.random.normal(jax.random.key(1), (50, 16))
        qt = quantize_weight(embed, (1,))
        toks = jnp.array([[3, 7], [11, 0]], jnp.int32)
        out = embedding_lookup(qt, toks, jnp.float32)
        ref = jnp.take(embed, toks, axis=0)
        err = float(jnp.abs(out - ref).max())
        assert err < float(jnp.abs(embed).max()) / 100


class TestQuantizedDenseServing:
    def test_tree_shape_and_dtypes(self):
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        qp = quantize_decode_params(params)
        layer = qp["layers"][0]
        assert isinstance(layer["wq"], QTensor)
        assert isinstance(layer["wkv"], QTensor)
        assert isinstance(layer["wo"], QTensor)
        assert isinstance(qp["embed"], QTensor)
        # Norms stay fp.
        assert not isinstance(layer["ln1"], QTensor)
        # int8 + scales is ~4x smaller than the fp32 original.
        orig = params["layers"][0]["w_gate"].nbytes
        quant = (layer["w_gate"].q.nbytes + layer["w_gate"].scale.nbytes)
        assert quant < 0.3 * orig

    def test_forward_logits_close(self):
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        qp = quantize_decode_params(params)
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, c.vocab_size)
        lf = forward(params, toks, c)
        lq = forward(qp, toks, c)
        denom = float(jnp.abs(lf).max())
        assert float(jnp.abs(lf - lq).max()) / denom < 0.1

    def test_fully_quantized_generate(self):
        """Weights int8 AND the KV cache int8 — the full serving config."""
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        qp = quantize_decode_params(params)
        prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, c.vocab_size)
        fp = generate(params, prompt, c, max_new_tokens=10, max_seq=32)
        q8 = generate(qp, prompt, c, max_new_tokens=10, max_seq=32,
                      kv_quant=True)
        assert q8.shape == fp.shape
        agree = float(jnp.mean(fp == q8))
        assert agree >= 0.6, f"greedy agreement {agree}"

    def test_quantized_prefill_logits_close(self):
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        qp = quantize_decode_params(params)
        prompt = jax.random.randint(jax.random.key(1), (1, 12), 0, c.vocab_size)
        lf, _ = prefill(params, prompt, c, max_seq=16)
        lq, _ = prefill(qp, prompt, c, max_seq=16)
        denom = float(jnp.abs(lf).max())
        assert float(jnp.abs(lf - lq).max()) / denom < 0.1


class TestQuantizedMoEServing:
    def test_moe_quantized_generate(self):
        c = MoEConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, d_ff=96, max_seq=32, dtype=jnp.float32,
                      n_experts=2, top_k=1, capacity_factor=4.0, moe_period=2)
        params = moe_init(c, jax.random.key(0))
        qp = quantize_decode_params(params)
        # Expert stacks quantize per-(expert, channel); router stays fp32.
        moe_layer = qp["layers"][1]
        assert isinstance(moe_layer["w_gate"], QTensor)
        assert moe_layer["w_gate"].scale.shape[0] == c.n_experts
        assert not isinstance(moe_layer["w_router"], QTensor)
        prompt = jax.random.randint(jax.random.key(1), (1, 6), 0, c.vocab_size)
        toks = generate(qp, prompt, c, max_new_tokens=4, max_seq=16,
                        kv_quant=True)
        assert toks.shape == (1, 4)
