"""Relay watcher + bench headline hygiene.

Two round-4 losses motivate these pins (VERDICT r4 missing #1/#2): the TPU
relay's uptime windows never coincided with a bench run, so no on-chip
numbers landed; and the one number the round did earn was unparseable
because the headline JSON line outgrew the driver's 2000-char tail. The
watcher must capture the moment the relay answers, and the headline must
stay under budget no matter how much evidence the probe returns.
"""

from __future__ import annotations

import json
import os
import time

import pytest

import tpu_composer.workload.probe as probe
import tpu_composer.workload.relay_watch as rw


def _full_tpu_result():
    return {
        "stages": {
            "backend_init": {"backend": "tpu", "n_devices": 1,
                             "device_kind": "TPU v5e"},
            "matmul": {"ok": True},
            "flash_attn": {"configs": [{"seq": 4096, "fwd_speedup": 1.4}],
                           "fwd_speedup_long": 1.4, "bwd_speedup_long": 1.1,
                           "numerics_ok": True},
            "qualify": {"tflops": 44.0, "mfu": 0.22, "backend": "tpu"},
            "qualify_large": {"tflops": 120.0, "mfu": 0.45},
            "decode": {"bf16_tokens_per_s": 900.0,
                       "int8_w_int8_kv_tokens_per_s": 1700.0,
                       "quant_speedup": 1.9},
        },
        "completed": ["devnodes", "backend_init", "matmul", "flash_attn",
                      "qualify", "qualify_large", "decode"],
    }


@pytest.fixture(autouse=True)
def _no_loopback_mode(monkeypatch):
    """Clear AXON_LOOPBACK_RELAY by default: these tests script
    reachability via probe_pool_endpoints, and loopback mode would
    otherwise turn every scripted 'down' poll into a direct capture
    attempt. Tests of the loopback path set the env themselves."""
    monkeypatch.delenv("AXON_LOOPBACK_RELAY", raising=False)


def _paths(tmp_path):
    return dict(
        log_path=str(tmp_path / "watch.jsonl"),
        archive_path=str(tmp_path / "probe.json"),
        pid_path=str(tmp_path / "watch.pid"),
        marker_path=str(tmp_path / "capture_in_progress.json"),
    )


def test_watch_captures_on_first_reachable_poll(tmp_path, monkeypatch):
    polls = iter([
        [{"endpoint": "127.0.0.1:8082", "reachable": False}],
        [{"endpoint": "127.0.0.1:8082", "reachable": False}],
        [{"endpoint": "127.0.0.1:8082", "reachable": True}],
    ])
    monkeypatch.setattr(probe, "probe_pool_endpoints", lambda **kw: next(polls))
    monkeypatch.setattr(probe, "staged_accelerator_probe",
                        lambda **kw: _full_tpu_result())
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.01, **p)
    assert rc == 0  # full capture → clean exit
    arch = json.loads(open(p["archive_path"]).read())
    assert arch["stages"]["flash_attn"]["fwd_speedup_long"] == 1.4
    assert "captured_at" in arch and "relay watcher" in arch["note"]
    events = [json.loads(l) for l in open(p["log_path"])]
    kinds = [e.get("event") for e in events]
    assert "capture_start" in kinds and "capture_done" in kinds
    # The two down polls were logged before the capture — the attempt log
    # is the round's evidence when the relay never answers.
    assert [e["up"] for e in events if "up" in e][:3] == [False, False, True]


def test_loopback_mode_attempts_capture_when_tcp_refuses(tmp_path,
                                                         monkeypatch):
    """r05 incident pin: under AXON_LOOPBACK_RELAY the relay is in-process —
    no TCP listener — so every preflight port refuses while the chip
    answers. The watcher must attempt the staged probe directly (the PJRT
    handshake inside backend_init IS the reachability test), bounded and
    without the cpu-fallback stages."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": False}],
    )
    seen_kwargs = []

    def _probe(**kw):
        seen_kwargs.append(kw)
        return _full_tpu_result()

    monkeypatch.setattr(probe, "staged_accelerator_probe", _probe)
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.01, **p)
    assert rc == 0  # full capture through the loopback path → clean exit
    arch = json.loads(open(p["archive_path"]).read())
    assert arch["stages"]["flash_attn"]["fwd_speedup_long"] == 1.4
    # The attempt was bounded: no retries, no cpu-fallback/AOT stages, and
    # a handshake budget far below the full 480 s probe default.
    kw = seen_kwargs[0]
    assert kw["retries"] == 0 and kw["fallbacks"] is False
    assert kw["timeouts"]["backend_init"] <= 180.0
    events = [json.loads(l) for l in open(p["log_path"])]
    assert any(e.get("loopback_attempt") for e in events)


def test_loopback_attempt_not_made_without_env(tmp_path, monkeypatch):
    """Outside loopback mode an all-refused preflight means the relay IS
    down — the watcher must not burn PJRT handshakes on it."""
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": False}],
    )

    def _boom(**kw):
        raise AssertionError("staged probe attempted without loopback env")

    monkeypatch.setattr(probe, "staged_accelerator_probe", _boom)
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.0001, **p)
    assert rc == 1  # deadline, no relay


def test_failed_loopback_attempt_cools_down(tmp_path, monkeypatch):
    """Chip-down loopback mode — the watcher's dominant state: a failed
    handshake must start a cooldown, not redial the relay every poll (the
    relay has wedged on handshake churn, and each attempt costs minutes)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": False}],
    )
    calls = []

    def _probe(**kw):
        calls.append(1)
        return {"stages": {"backend_init": {"error": "hang"}},
                "completed": ["devnodes"], "failed_stage": "backend_init"}

    monkeypatch.setattr(probe, "staged_accelerator_probe", _probe)
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.0003,  # ~1 s of polls
                        min_capture_gap_s=0.0, **p)
    assert rc == 1
    events = [json.loads(l) for l in open(p["log_path"])]
    n_polls = sum(1 for e in events if "up" in e)
    assert n_polls > 10  # many polls happened...
    assert len(calls) == 1  # ...but the relay was dialed once, then cooled


def test_failed_loopback_attempt_does_not_charge_capture_gap(tmp_path,
                                                             monkeypatch):
    """A failed handshake is a down-relay datum, not a capture: only the
    cooldown prices it. Gap-pricing failures would sleep the watcher
    through an uptime window the size of the one it exists to catch (the
    r05 window was ~6 min; the default gap is 10)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": False}],
    )
    calls = []

    def _probe(**kw):
        calls.append(1)
        if len(calls) == 1:  # dead on the first dial...
            return {"stages": {"backend_init": {"error": "hang"}},
                    "completed": ["devnodes"],
                    "failed_stage": "backend_init"}
        return _full_tpu_result()  # ...the window opened by the second

    monkeypatch.setattr(probe, "staged_accelerator_probe", _probe)
    # Virtual clock: each sleep advances a minute, so the 180 s cooldown
    # expires after a few polls while the 3600 s gap — which a failure
    # must NOT charge — would outlast the whole watch if it did.
    t = [0.0]
    monkeypatch.setattr(time, "monotonic", lambda: t[0])
    monkeypatch.setattr(time, "sleep",
                        lambda s: t.__setitem__(0, t[0] + max(s, 60.0)))
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.5,
                        min_capture_gap_s=3600.0, **p)
    assert rc == 0  # second dial captured despite the unexpired 3600s gap
    assert len(calls) == 2
    events = [json.loads(l) for l in open(p["log_path"])]
    starts = [i for i, e in enumerate(events)
              if e.get("event") == "capture_start"]
    assert len(starts) == 2
    # ...and the cooldown gated the redial: with sleeps advancing 60
    # virtual seconds each and a 180 s cooldown, at least two non-attempt
    # polls sit between the two capture_start events.
    between = [e for e in events[starts[0] + 1:starts[1]] if "up" in e]
    assert len(between) >= 2


def test_capture_marker_guards_concurrent_handshakes(tmp_path, monkeypatch):
    """While the watcher's staged probe owns the relay, a concurrent
    would-be client (bench.py) must see capture_in_progress() and wait —
    overlapping PJRT handshakes have wedged the relay (r05)."""
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": False}],
    )
    p = _paths(tmp_path)
    marker = p["marker_path"]
    seen_during = []

    def _probe(**kw):
        # The marker must be on disk exactly while the probe runs, naming
        # this process — that is what a concurrent bench in ANOTHER
        # process would read as in-progress. (From the same pid,
        # capture_in_progress deliberately reads False: one's own marker
        # cannot be a concurrent client.)
        with open(marker) as f:
            seen_during.append(json.load(f)["pid"])
        assert rw.capture_in_progress(marker) is False  # own-pid exclusion
        return _full_tpu_result()

    monkeypatch.setattr(probe, "staged_accelerator_probe", _probe)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.01, **p)
    assert rc == 0
    assert seen_during == [os.getpid()]
    assert not os.path.exists(marker)  # cleared after
    assert rw.wait_for_capture_idle(timeout_s=0.1, path=marker) is True


def test_live_foreign_marker_reads_in_progress_and_defers_watcher(
        tmp_path, monkeypatch):
    """A marker naming a live OTHER process blocks clients — and a watcher
    poll that finds the relay up must defer its capture, not dial."""
    marker = str(tmp_path / "capture_in_progress.json")
    # pid 1 is always alive; record its true start time so the pid-reuse
    # check passes.
    with open(marker, "w") as f:
        json.dump({"pid": 1, "start": rw._proc_start_time(1)}, f)
    assert rw.capture_in_progress(marker) is True
    assert rw.wait_for_capture_idle(timeout_s=0.05, path=marker,
                                    poll_s=0.01) is False

    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": True}],
    )

    def _boom(**kw):
        raise AssertionError("dialed the relay while another client held it")

    monkeypatch.setattr(probe, "staged_accelerator_probe", _boom)
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.0001, **p)
    assert rc == 1  # deadline — every capture deferred
    events = [json.loads(l) for l in open(p["log_path"])]
    assert any(e.get("event") == "capture_deferred" for e in events)


def test_hold_capture_marker_acquire_semantics(tmp_path):
    """The marker is claimed with O_CREAT|O_EXCL — check and claim are one
    syscall — and a loser must never unlink the winner's marker."""
    marker = str(tmp_path / "capture_in_progress.json")
    with rw.hold_capture_marker(marker) as held:
        assert held is True
        rec = json.load(open(marker))
        assert rec["pid"] == os.getpid()
    assert not os.path.exists(marker)  # released on exit
    # Foreign live marker → not acquired, and NOT cleared by the loser.
    with open(marker, "w") as f:
        json.dump({"pid": 1, "start": rw._proc_start_time(1)}, f)
    with rw.hold_capture_marker(marker) as held:
        assert held is False
    assert os.path.exists(marker)
    # Stale marker (dead pid) → reaped, then claimed.
    with open(marker, "w") as f:
        json.dump({"pid": 2**22 + 1234, "start": "999999"}, f)
    with rw.hold_capture_marker(marker) as held:
        assert held is True
        assert json.load(open(marker))["pid"] == os.getpid()
    assert not os.path.exists(marker)


def test_try_acquire_marker_three_states(tmp_path, monkeypatch):
    """acquired / held-by-other / unguarded are distinct outcomes, and
    only an ACQUIRED marker is unlinked on exit — an unguarded client
    (filesystem refused the claim) must never delete a live peer's
    marker."""
    marker = str(tmp_path / "capture_in_progress.json")
    assert rw._try_acquire_marker(marker) == rw.MARKER_ACQUIRED
    os.unlink(marker)
    # Foreign live marker → held.
    with open(marker, "w") as f:
        json.dump({"pid": 1, "start": rw._proc_start_time(1)}, f)
    assert rw._try_acquire_marker(marker) == rw.MARKER_HELD
    # Filesystem refusing the claim (EACCES and friends) → unguarded, and
    # the hold context proceeds WITHOUT unlinking the peer's marker on
    # exit — the transient-OSError path used to delete it.
    real_open = os.open

    def _refuse(path, flags, *a, **kw):
        if path == marker and flags & os.O_EXCL:
            raise PermissionError(13, "injected EACCES", path)
        return real_open(path, flags, *a, **kw)

    monkeypatch.setattr(os, "open", _refuse)
    assert rw._try_acquire_marker(marker) == rw.MARKER_UNGUARDED
    with rw.hold_capture_marker(marker) as held:
        assert held is True  # unguarded still proceeds (capture > lockout)
    assert os.path.exists(marker)  # the peer's marker survived
    monkeypatch.undo()
    assert json.load(open(marker))["pid"] == 1


def test_watch_relay_serializes_on_canonical_marker(tmp_path, monkeypatch):
    """A watcher pointed at a NON-default archive path must still defer to
    a client holding the (explicitly passed) marker — exclusion is keyed
    on marker_path, never derived from archive_path."""
    marker = str(tmp_path / "shared_marker.json")
    with open(marker, "w") as f:
        json.dump({"pid": 1, "start": rw._proc_start_time(1)}, f)
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "127.0.0.1:8082", "reachable": True}],
    )

    def _boom(**kw):
        raise AssertionError("dialed while the canonical marker was held")

    monkeypatch.setattr(probe, "staged_accelerator_probe", _boom)
    p = _paths(tmp_path)
    p["archive_path"] = str(tmp_path / "elsewhere" / "archive.json")
    p["marker_path"] = marker
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.0001, **p)
    assert rc == 1
    events = [json.loads(l) for l in open(p["log_path"])]
    assert any(e.get("event") == "capture_deferred" for e in events)


def test_stale_capture_marker_reads_idle(tmp_path):
    marker = str(tmp_path / "capture_in_progress.json")
    # Dead pid → stale marker → idle (a crashed watcher must not block
    # every future bench for the round).
    with open(marker, "w") as f:
        json.dump({"pid": 2**22 + 1234, "start": "999999"}, f)
    assert rw.capture_in_progress(marker) is False
    # Garbage marker → idle.
    with open(marker, "w") as f:
        f.write("not json")
    assert rw.capture_in_progress(marker) is False
    # No marker → idle, and the wait returns immediately.
    os.unlink(marker)
    assert rw.wait_for_capture_idle(timeout_s=0.1, path=marker) is True


def test_partial_capture_archived_but_watch_continues(tmp_path, monkeypatch):
    """A relay that flaps mid-probe still yields an archive (better than
    nothing) but the watcher keeps polling for a full capture."""
    partial = {
        "stages": {"backend_init": {"backend": "tpu"}, "matmul": {"ok": True}},
        "completed": ["devnodes", "backend_init", "matmul"],
        "failed_stage": "flash_attn",
    }
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "e", "reachable": True}],
    )
    monkeypatch.setattr(probe, "staged_accelerator_probe", lambda **kw: partial)
    p = _paths(tmp_path)
    rc = rw.watch_relay(poll_s=0.005, max_hours=0.05 / 3600.0,
                        min_capture_gap_s=0.0, **p)
    assert rc == 1  # deadline, not capture_complete
    arch = json.loads(open(p["archive_path"]).read())
    assert arch["failed_stage"] == "flash_attn"
    events = [json.loads(l) for l in open(p["log_path"])]
    dones = [e for e in events if e.get("event") == "capture_done"]
    assert dones and all(d["full"] is False for d in dones)


def test_non_tpu_probe_never_overwrites_archive(tmp_path, monkeypatch):
    """A capture attempt that fell back to CPU (relay died between poll and
    handshake) must not clobber a real on-TPU archive."""
    cpu = {"stages": {"backend_init": {"backend": "cpu"}},
           "completed": ["devnodes", "backend_init"]}
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "e", "reachable": True}],
    )
    monkeypatch.setattr(probe, "staged_accelerator_probe", lambda **kw: cpu)
    p = _paths(tmp_path)
    with open(p["archive_path"], "w") as f:
        json.dump({"captured_at": "X", "stages": {}}, f)
    rc = rw.watch_relay(poll_s=0.005, max_hours=0.05 / 3600.0,
                        min_capture_gap_s=0.0, **p)
    assert rc == 1
    assert json.loads(open(p["archive_path"]).read())["captured_at"] == "X"


def test_second_watcher_refuses_to_start(tmp_path, monkeypatch):
    monkeypatch.setattr(
        probe, "probe_pool_endpoints",
        lambda **kw: [{"endpoint": "e", "reachable": False}],
    )
    p = _paths(tmp_path)
    # A live watcher (this very process, start time recorded) blocks.
    with open(p["pid_path"], "w") as f:
        start = rw._proc_start_time(os.getpid()) or ""
        f.write(f"{os.getpid()} {start}")
    rc = rw.watch_relay(poll_s=0.01, max_hours=0.001, **p)
    assert rc == 2
    # A dead pid must not block.
    with open(p["pid_path"], "w") as f:
        f.write("999999999")
    rc = rw.watch_relay(poll_s=0.005, max_hours=0.02 / 3600.0, **p)
    assert rc == 1
    # A RECYCLED pid (alive, but different kernel start time than the
    # pidfile recorded) must not block either — the SIGKILL'd-watcher +
    # pid-reuse case that would otherwise silently cost a round of
    # hardware evidence.
    with open(p["pid_path"], "w") as f:
        f.write(f"{os.getpid()} 12345")  # wrong start time on purpose
    rc = rw.watch_relay(poll_s=0.005, max_hours=0.02 / 3600.0, **p)
    assert rc == 1
    # LEGACY pid-only pidfile whose pid was recycled by a non-watcher
    # (this pytest process): no start time to compare, so the cmdline
    # fallback must notice it isn't a watcher and let the new one start.
    with open(p["pid_path"], "w") as f:
        f.write(str(os.getpid()))
    rc = rw.watch_relay(poll_s=0.005, max_hours=0.02 / 3600.0, **p)
    assert rc == 1


def test_full_capture_predicate():
    assert rw.probe_is_full_tpu_capture(_full_tpu_result())
    r = _full_tpu_result()
    r["stages"]["backend_init"]["backend"] = "cpu"
    assert not rw.probe_is_full_tpu_capture(r)
    r = _full_tpu_result()
    r["completed"].remove("decode")
    assert not rw.probe_is_full_tpu_capture(r)
    r = _full_tpu_result()
    del r["stages"]["flash_attn"]["fwd_speedup_long"]
    assert not rw.probe_is_full_tpu_capture(r)


def test_headline_stays_under_driver_tail_budget():
    """The exact failure of BENCH_r04: the headline embedded a multi-KB
    probe blob. Build a worst-case accelerator record (live failure + big
    archive + AOT block + CPU fallback) and assert the summarized headline
    fits the driver's tail with margin."""
    import bench

    archived = _full_tpu_result()
    archived["captured_at"] = "2026-07-30T00:00:00Z"
    # Bloat the raw record the way real probes do.
    archived["stages"]["devnodes"] = {"env": {f"K{i}": "v" * 40
                                              for i in range(40)}}
    archived["stages"]["flash_attn"]["configs"] = [
        {"seq": s, "flash_fwd_ms": 1.0, "ref_fwd_ms": 2.0,
         "flash_bwd_ms": 3.0, "ref_bwd_ms": 4.0, "fwd_speedup": 1.5,
         "bwd_speedup": 1.2} for s in (1024, 2048, 4096, 8192)
    ]
    accel = {
        "stages": {"devnodes": archived["stages"]["devnodes"],
                   "backend_init": {"backend": "cpu"}},
        "completed": ["devnodes"],
        "failed_stage": "backend_init",
        "diagnosis": {"stderr_tail": ["x" * 80] * 40,
                      "blocked_call": "y" * 200},
        "archived_tpu_probe": archived,
        "cpu_fallback": {"stages": {"qualify": {"tflops": 0.1}},
                         "completed": ["backend_init", "qualify"]},
        "tpu_aot_compile": {
            "flash_grad_v5e": {"ok": True, "seconds": 30.0},
            "train_step_v5e_2x4": {"ok": True, "mesh": {"dp": 2, "sp": 2,
                                                        "tp": 2},
                                   "collectives": {"per_axis_bytes":
                                                   {"sp": 278756}}},
            "moe_train_step_v5e_4x4": {"ok": True,
                                       "collectives": {"per_axis_bytes":
                                                       {"ep": 3166372}}},
            "qualify_large_hbm": {"ok": True, "peak_gib": 9.3},
            "decode_serving_v5e": {"ok": True},
        },
    }
    out = {
        "metric": "attach_to_ready_p50", "value": 123.456, "unit": "ms",
        "vs_baseline": 242.7,
        "extra": {
            "attach_p90_ms": 127.9, "attach_max_ms": 130.0, "cycles": 20,
            "injected_store_latency_ms": 10.0, "raw_inproc_p50_ms": 40.0,
            "raw_inproc_p90_ms": 45.0, "baseline_p50_ms": 30000.0,
            "accelerator": bench.summarize_accelerator(accel),
            "full_record": "bench_artifacts/bench_full.json",
        },
    }
    line = json.dumps(out)
    assert len(line) <= bench.HEADLINE_BUDGET_CHARS, len(line)
    # And the summary still carries the evidence that matters.
    acc = out["extra"]["accelerator"]
    assert acc["archived_tpu_probe"]["stages"]["flash_attn"][
        "fwd_speedup_long"] == 1.4
    assert acc["archived_tpu_probe"]["stages"]["decode"][
        "quant_speedup"] == 1.9
    assert acc["tpu_aot_compile"]["qualify_large_hbm"] is True
