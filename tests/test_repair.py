"""Self-healing data plane: post-Ready failure detection, automatic member
repair, and repair-storm containment.

Tier-1 acceptance spine for ISSUE 7: a chip dying under a Ready slice is
detected by damped health probes (or the syncer's device-vanished pass),
the member transitions to a durable Degraded state with a structured
failure record, and the request controller drives a make-before-break
repair — replacement placed on healthy capacity, attached, then the failed
member force-detached after the drain grace — bounded by the per-request
surge budget and the fleet-level repair breaker (a brownout freezes repairs
instead of mass-detaching). The 100-cycle soak is in test_repair_soak.py
(marked slow/repair); everything here runs in tier-1.
"""

from __future__ import annotations

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.publisher import node_quarantined
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    ComposableResourceSpec,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.dra import DeviceTaintRule
from tpu_composer.api.types import (
    ANNOTATION_REPLACES,
    REPAIR_DETACH_ONLY,
    REPAIR_NONE,
    REQUEST_STATE_RUNNING,
    RESOURCE_STATE_DEGRADED,
    RESOURCE_STATE_ONLINE,
    RESOURCE_STATE_REPAIRING,
)
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
    RepairConfig,
    RequestTiming,
)
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.fabric.chaos import ChaosFabricProvider
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import (
    FabricError,
    UnsupportedRepair,
)
from tpu_composer.runtime.metrics import (
    composed_chips,
    repair_breaker_open,
    repairs_total,
)
from tpu_composer.runtime.store import Store

MODEL = "tpu-v4"


def make_world(nodes=4, chips=64, failure_threshold=2, recovery_threshold=1,
               node_degrade_threshold=0, repair=None, spec_kw=None,
               pool_cls=InMemoryPool):
    """Step-driven harness (no Manager threads): store + chaos-wrapped mock
    pool + both reconcilers with fast repair timing."""
    store = Store()
    for i in range(nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = pool_cls(chips={MODEL: chips})
    chaos = ChaosFabricProvider(pool)
    agent = FakeNodeAgent(pool=pool)
    req_rec = ComposabilityRequestReconciler(
        store, chaos,
        timing=RequestTiming(updating_poll=0.01, cleaning_poll=0.01,
                             running_poll=5.0, repair_poll=0.01),
        repair=repair or RepairConfig(),
    )
    res_rec = ComposableResourceReconciler(
        store, chaos, agent,
        timing=ResourceTiming(
            health_failure_threshold=failure_threshold,
            health_recovery_threshold=recovery_threshold,
            node_degrade_threshold=node_degrade_threshold,
        ),
    )
    return store, pool, chaos, req_rec, res_rec


def make_request(store, name="req-1", size=8, **spec_kw):
    store.create(ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(
            resource=ResourceDetails(type="tpu", model=MODEL, size=size),
            **spec_kw,
        ),
    ))


def converged(store, name="req-1"):
    req = store.try_get(ComposabilityRequest, name)
    if req is None:
        return False
    live = [c for c in store.list(ComposableResource) if not c.being_deleted]
    return (
        req.status.state == REQUEST_STATE_RUNNING
        and len(live) == req.status.slice.num_hosts
        and all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
    )


def pump(store, req_rec, res_rec, name="req-1", steps=80, invariant=None,
         done=None):
    """One scheduler-free event loop turn per step: request then every
    resource, absorbing expected fabric errors like the worker loop's
    backoff does. Stops when ``done()`` (default: the request converged —
    Running with every member Online at full count)."""
    finished = done or (lambda: converged(store, name))
    for _ in range(steps):
        try:
            req_rec.reconcile(name)
        except FabricError:
            pass
        for c in store.list(ComposableResource):
            try:
                res_rec.reconcile(c.metadata.name)
            except FabricError:
                pass
        if invariant is not None:
            invariant()
        if finished():
            return store.get(ComposabilityRequest, name)
    return store.get(ComposabilityRequest, name)


def to_running(store, req_rec, res_rec, name="req-1"):
    req = pump(store, req_rec, res_rec, name)
    assert req.status.state == REQUEST_STATE_RUNNING, req.status.to_dict()
    return req


def members(store):
    return [c for c in store.list(ComposableResource) if not c.being_deleted]


def no_duplicate_attachments(pool):
    ids = [d.device_id for d in pool.get_resources()]
    assert len(ids) == len(set(ids)), f"duplicate attachments: {ids}"


# ---------------------------------------------------------------------------
# Replace policy: make-before-break
# ---------------------------------------------------------------------------

class TestReplaceRepair:
    def test_dead_chip_member_is_replaced_make_before_break(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = next(c for c in members(store) if c.spec.worker_id == 1)
        old_name, old_node = victim.name, victim.spec.target_node
        started = repairs_total.value(outcome="started")
        replaced = repairs_total.value(outcome="replaced")

        pool.kill_device(victim.status.device_ids[0])

        # Make-before-break invariant: the failed member may only disappear
        # after its replacement is Online (checked every pump turn).
        seen = {"repl_online_before_old_gone": False}

        def invariant():
            no_duplicate_attachments(pool)
            old = store.try_get(ComposableResource, old_name)
            repl = next(
                (c for c in store.list(ComposableResource)
                 if c.metadata.annotations.get(ANNOTATION_REPLACES) == old_name),
                None,
            )
            if repl is not None and repl.status.state == RESOURCE_STATE_ONLINE:
                seen["repl_online_before_old_gone"] = True
            if old is None or old.being_deleted:
                assert seen["repl_online_before_old_gone"], (
                    "failed member detached before its replacement was Online"
                )

        req = pump(
            store, req_rec, res_rec, invariant=invariant,
            done=lambda: (
                store.try_get(ComposableResource, old_name) is None
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        live = members(store)
        assert len(live) == 2
        assert all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
        # The replacement took over worker 1 on a fresh node.
        new_w1 = next(c for c in live if c.spec.worker_id == 1)
        assert new_w1.name != old_name
        assert new_w1.spec.target_node != old_node
        assert new_w1.metadata.annotations.get(ANNOTATION_REPLACES) == old_name
        # Authoritative coordinates follow the repair.
        assert req.status.slice.worker_hostnames[1] == new_w1.spec.target_node
        # The dead chip left circulation; no member holds it.
        attached_ids = {d.device_id for d in pool.get_resources()}
        assert not any(d in attached_ids for d in [victim.status.device_ids[0]])
        assert pool.dead_chips(MODEL) == 1
        assert repairs_total.value(outcome="started") == started + 1
        assert repairs_total.value(outcome="replaced") == replaced + 1

    def test_surge_budget_bounds_concurrent_repairs(self):
        store, pool, chaos, req_rec, res_rec = make_world(nodes=8)
        make_request(store, size=16, max_concurrent_repairs=1)
        to_running(store, req_rec, res_rec)
        victims = [c for c in members(store) if c.spec.worker_id in (0, 2)]
        victim_names = {v.name for v in victims}
        for v in victims:
            pool.kill_device(v.status.device_ids[0])

        max_repairing = {"n": 0}

        def invariant():
            repairing = [
                c for c in store.list(ComposableResource)
                if c.status.state == RESOURCE_STATE_REPAIRING
            ]
            max_repairing["n"] = max(max_repairing["n"], len(repairing))
            assert len(repairing) <= 1, (
                f"surge budget exceeded: {[c.name for c in repairing]}"
            )
            no_duplicate_attachments(pool)

        req = pump(
            store, req_rec, res_rec, steps=160, invariant=invariant,
            done=lambda: (
                not (victim_names
                     & {c.name for c in store.list(ComposableResource)})
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        live = members(store)
        assert len(live) == 4
        assert all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
        assert not (victim_names & {c.name for c in live})
        assert max_repairing["n"] == 1  # repairs actually serialized
        assert pool.dead_chips(MODEL) == 2

    def test_repair_waits_when_no_healthy_capacity(self):
        # 2 nodes, 2-host slice: nowhere to place a replacement — the repair
        # driver surfaces the failure and retries; the degraded member is
        # NOT detached (better a degraded member than a smaller slice).
        store, pool, chaos, req_rec, res_rec = make_world(nodes=2)
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        pool.kill_device(victim.status.device_ids[0])
        failed_before = repairs_total.value(outcome="failed")
        req = pump(
            store, req_rec, res_rec, steps=30,
            done=lambda: repairs_total.value(outcome="failed") > failed_before,
        )
        assert repairs_total.value(outcome="failed") > failed_before
        v = store.get(ComposableResource, victim.name)
        assert v.status.state == RESOURCE_STATE_DEGRADED
        assert "repair of" in req.status.error


class _NoRepairPool(InMemoryPool):
    def repair_slice_member(self, slice_name, worker_id, node):
        raise UnsupportedRepair("this pool cannot swap chips in place")


class TestPolicies:
    def test_unsupported_repair_falls_back_to_resolve(self):
        store, pool, chaos, req_rec, res_rec = make_world(
            pool_cls=_NoRepairPool
        )
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        fallback_before = repairs_total.value(outcome="fallback")
        pool.kill_device(victim.status.device_ids[0])
        req = pump(
            store, req_rec, res_rec, steps=160,
            done=lambda: (
                victim.name not in {c.name for c in members(store)}
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        live = members(store)
        assert len(live) == 2
        assert all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
        assert victim.name not in {c.name for c in live}
        assert repairs_total.value(outcome="fallback") == fallback_before + 1
        no_duplicate_attachments(pool)

    def test_detach_only_policy_detaches_and_resolves(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store, repair_policy=REPAIR_DETACH_ONLY)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        detached_before = repairs_total.value(outcome="detached")
        pool.kill_device(victim.status.device_ids[0])
        req = pump(
            store, req_rec, res_rec, steps=160,
            done=lambda: (
                victim.name not in {c.name for c in members(store)}
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        live = members(store)
        assert len(live) == 2
        assert victim.name not in {c.name for c in live}
        assert repairs_total.value(outcome="detached") == detached_before + 1
        no_duplicate_attachments(pool)

    def test_none_policy_leaves_degraded_member_for_operator(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store, repair_policy=REPAIR_NONE)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        pool.kill_device(victim.status.device_ids[0])
        pump(
            store, req_rec, res_rec, steps=20,
            done=lambda: "repairPolicy=None" in store.get(
                ComposabilityRequest, "req-1"
            ).status.error,
        )
        v = store.get(ComposableResource, victim.name)
        assert v.status.state == RESOURCE_STATE_DEGRADED
        assert v.status.failure is not None
        # No replacement was placed, nothing was detached.
        assert len(members(store)) == 2
        assert not any(
            c.metadata.annotations.get(ANNOTATION_REPLACES)
            for c in store.list(ComposableResource)
        )
        req = store.get(ComposabilityRequest, "req-1")
        assert "repairPolicy=None" in req.status.error
        evs = req_rec.recorder.for_object(kind="ComposabilityRequest",
                                          name="req-1")
        assert any(e.reason == "DegradedNoRepair" for e in evs)
        # In-place recovery clears the stale operator-action-required error.
        pool.revive_device(victim.status.device_ids[0])
        req = pump(
            store, req_rec, res_rec, steps=40,
            done=lambda: (
                converged(store)
                and not store.get(ComposabilityRequest, "req-1").status.error
            ),
        )
        assert req.status.error == ""

    def test_none_policy_does_not_starve_lost_member_recovery(self):
        """A sibling sitting Degraded under repairPolicy=None must not
        block the full re-solve when ANOTHER member's child object is lost
        outright."""
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store, repair_policy=REPAIR_NONE)
        to_running(store, req_rec, res_rec)
        sick, lost = sorted(members(store), key=lambda c: c.spec.worker_id)
        pool.kill_device(sick.status.device_ids[0])
        pump(store, req_rec, res_rec, steps=20,
             done=lambda: store.get(
                 ComposableResource, sick.name
             ).status.state == RESOURCE_STATE_DEGRADED)
        # Lose the other member's child entirely (node-GC analog).
        store.delete(ComposableResource, lost.name)
        req = pump(store, req_rec, res_rec, steps=200)
        assert req.status.state == REQUEST_STATE_RUNNING
        live = members(store)
        assert len(live) == 2
        assert all(c.status.state == RESOURCE_STATE_ONLINE for c in live)


# ---------------------------------------------------------------------------
# Storm containment: the fleet-level repair breaker
# ---------------------------------------------------------------------------

class TestRepairBreaker:
    def test_brownout_freezes_repairs_instead_of_mass_detach(self):
        store, pool, chaos, req_rec, res_rec = make_world(
            nodes=8, repair=RepairConfig(breaker_fraction=0.5,
                                         breaker_min_members=2,
                                         min_degraded_seconds=0.5),
        )
        make_request(store, size=16)  # 4 members
        to_running(store, req_rec, res_rec)
        before = {c.name for c in members(store)}
        # Brownout: 3 of 4 members' nodes go dark post-Ready (the fabric
        # still answers — it just reports Critical everywhere).
        victims = sorted(members(store), key=lambda c: c.spec.worker_id)[:3]
        for v in victims:
            chaos.degrade_node(v.spec.target_node)
        pump(store, req_rec, res_rec, steps=30,
             done=lambda: repair_breaker_open.value() == 1.0)
        pump(store, req_rec, res_rec, steps=5, done=lambda: False)
        # All three degraded, breaker open, NOTHING detached or replaced.
        assert repair_breaker_open.value() == 1.0
        live = members(store)
        assert {c.name for c in live} == before
        assert sum(
            1 for c in live if c.status.state == RESOURCE_STATE_DEGRADED
        ) == 3
        evs = req_rec.recorder.for_object(kind="ComposabilityRequest",
                                          name="req-1")
        assert any(e.reason == "RepairsFrozen" for e in evs)
        # Brownout lifts: members RECOVER in place (no repairs ever ran).
        chaos.heal()
        req = pump(store, req_rec, res_rec, steps=60)
        assert req.status.state == REQUEST_STATE_RUNNING
        assert {c.name for c in members(store)} == before
        assert all(
            c.status.state == RESOURCE_STATE_ONLINE for c in members(store)
        )
        req_rec.reconcile("req-1")  # one steady pass recomputes the breaker
        assert repair_breaker_open.value() == 0.0

    def test_single_failure_on_small_fleet_still_repairs(self):
        # breaker_min_members guards the degenerate fraction: 1 degraded of
        # 2 attached is 50% but NOT a brownout.
        store, pool, chaos, req_rec, res_rec = make_world(
            repair=RepairConfig(breaker_fraction=0.4, breaker_min_members=4),
        )
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        pool.kill_device(victim.status.device_ids[0])
        req = pump(
            store, req_rec, res_rec, steps=120,
            done=lambda: (
                victim.name not in {c.name for c in members(store)}
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        assert victim.name not in {c.name for c in members(store)}


# ---------------------------------------------------------------------------
# Node escalation (PR 1 quarantine path, distinct reason)
# ---------------------------------------------------------------------------

class TestNodeEscalation:
    def test_repeated_post_ready_failures_quarantine_the_node(self):
        store, pool, chaos, req_rec, res_rec = make_world(
            node_degrade_threshold=2,
        )
        # Two independent single-host slices on worker-0.
        for i, slice_name in enumerate(["s-a", "s-b"]):
            pool.reserve_slice(slice_name, MODEL, "2x2x1", ["worker-0"])
            store.create(ComposableResource(
                metadata=ObjectMeta(name=f"r{i}"),
                spec=ComposableResourceSpec(
                    type="tpu", model=MODEL, target_node="worker-0",
                    chip_count=4, slice_name=slice_name, worker_id=0,
                    topology="2x2x1",
                ),
            ))
            res_rec.reconcile(f"r{i}")  # "" -> Attaching
            res_rec.reconcile(f"r{i}")  # Attaching -> Online
            assert store.get(
                ComposableResource, f"r{i}"
            ).status.state == RESOURCE_STATE_ONLINE
        for i in range(2):
            cr = store.get(ComposableResource, f"r{i}")
            pool.kill_device(cr.status.device_ids[0])
            for _ in range(res_rec.timing.health_failure_threshold):
                res_rec.reconcile(f"r{i}")
            assert store.get(
                ComposableResource, f"r{i}"
            ).status.state == RESOURCE_STATE_DEGRADED
        assert node_quarantined(store, "worker-0")
        marker = next(
            r for r in store.list(DeviceTaintRule)
            if r.spec.node_name == "worker-0" and not r.spec.device_uuid
        )
        assert "post-ready-failures" in marker.spec.reason


# ---------------------------------------------------------------------------
# Syncer arm: device vanished from the fabric listing
# ---------------------------------------------------------------------------

class TestSyncerVanishDetection:
    def _online_member(self, store, pool, chaos, res_rec):
        pool.reserve_slice("s1", MODEL, "2x2x1", ["worker-0"])
        store.create(ComposableResource(
            metadata=ObjectMeta(name="r0"),
            spec=ComposableResourceSpec(
                type="tpu", model=MODEL, target_node="worker-0",
                chip_count=4, slice_name="s1", worker_id=0, topology="2x2x1",
            ),
        ))
        res_rec.reconcile("r0")
        res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        return cr

    def test_vanished_device_degrades_after_damping_window(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        cr = self._online_member(store, pool, chaos, res_rec)
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=100.0,
                                vanish_threshold=2)
        chaos.vanish_device(cr.status.device_ids[0])
        syncer.sync_once(now=0.0)
        # Damped: one glitchy listing writes nothing.
        assert store.get(
            ComposableResource, "r0"
        ).status.state == RESOURCE_STATE_ONLINE
        syncer.sync_once(now=1.0)
        cr = store.get(ComposableResource, "r0")
        assert cr.status.state == RESOURCE_STATE_DEGRADED
        assert cr.status.failure is not None
        assert cr.status.failure.source == "syncer"
        assert cr.status.failure.reason == "device-vanished"

    def test_listing_blip_does_not_count_toward_vanish(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        cr = self._online_member(store, pool, chaos, res_rec)
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=100.0,
                                vanish_threshold=2)
        # Fabric unreachable: sync_once raises; unreachable must never
        # masquerade as vanished.
        chaos.fail_op("get_resources", times=2)
        for _ in range(2):
            with pytest.raises(FabricError):
                syncer.sync_once(now=0.0)
        syncer.sync_once(now=1.0)
        syncer.sync_once(now=2.0)
        assert store.get(
            ComposableResource, "r0"
        ).status.state == RESOURCE_STATE_ONLINE

    def test_vanished_member_recovers_when_devices_reappear(self):
        """Listing-based recovery mirrors listing-based detection: the
        member's own handler must NOT probe-recover a device-vanished
        degrade (health answers OK while the attachment is missing — the
        livelock); the syncer recovers it when the listing reports the
        devices again."""
        store, pool, chaos, req_rec, res_rec = make_world()
        cr = self._online_member(store, pool, chaos, res_rec)
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=100.0,
                                vanish_threshold=2)
        dev = cr.status.device_ids[0]
        chaos.vanish_device(dev)
        syncer.sync_once(now=0.0)
        syncer.sync_once(now=1.0)
        assert store.get(
            ComposableResource, "r0"
        ).status.state == RESOURCE_STATE_DEGRADED
        # Probe-healthy reconciles must NOT flip it back (the probe path
        # would: pool health is OK — only the listing lies).
        for _ in range(res_rec.timing.health_recovery_threshold + 1):
            res_rec.reconcile("r0")
        assert store.get(
            ComposableResource, "r0"
        ).status.state == RESOURCE_STATE_DEGRADED
        # Devices reappear -> the syncer recovers the member.
        chaos.unvanish_device(dev)
        syncer.sync_once(now=2.0)
        cr = store.get(ComposableResource, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert cr.status.failure is None

    def test_vanished_member_is_repaired_despite_healthy_probe(self):
        """The repair driver's last-look health probe must not veto repair
        of a device-vanished member — its evidence is the listing, and a
        healthy probe is exactly the failure mode being detected."""
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = members(store)[0]
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=100.0,
                                vanish_threshold=2)
        for dev in victim.status.device_ids:
            chaos.vanish_device(dev)
        syncer.sync_once(now=0.0)
        syncer.sync_once(now=1.0)
        assert store.get(
            ComposableResource, victim.name
        ).status.state == RESOURCE_STATE_DEGRADED
        req = pump(
            store, req_rec, res_rec, steps=160,
            done=lambda: (
                victim.name not in {c.name
                                    for c in store.list(ComposableResource)}
                and converged(store)
            ),
        )
        assert req.status.state == REQUEST_STATE_RUNNING
        assert victim.name not in {c.name for c in members(store)}

    def test_reappearing_device_resets_the_vanish_clock(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        cr = self._online_member(store, pool, chaos, res_rec)
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=100.0,
                                vanish_threshold=2)
        dev = cr.status.device_ids[0]
        chaos.vanish_device(dev)
        syncer.sync_once(now=0.0)
        chaos.unvanish_device(dev)
        syncer.sync_once(now=1.0)  # reappeared — clock resets
        chaos.vanish_device(dev)
        syncer.sync_once(now=2.0)  # missing pass #1 again
        assert store.get(
            ComposableResource, "r0"
        ).status.state == RESOURCE_STATE_ONLINE


# ---------------------------------------------------------------------------
# fabric_attached staleness (satellite: gauge must not zero on a blip)
# ---------------------------------------------------------------------------

class TestFabricAttachedStaleness:
    def test_unreachable_fabric_returns_none_not_empty(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        chaos.blackout()
        assert res_rec.fabric_attached("worker-0") is None
        chaos.heal()
        assert res_rec.fabric_attached("worker-0") == []

    def test_gauge_keeps_last_value_through_a_blip(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        make_request(store, size=4)
        to_running(store, req_rec, res_rec)
        node = members(store)[0].spec.target_node
        assert composed_chips.value(node=node) == 4
        chaos.blackout()
        res_rec._refresh_composed_gauge(node)
        assert composed_chips.value(node=node) == 4  # stale, not zero
        chaos.heal()
        res_rec._refresh_composed_gauge(node)
        assert composed_chips.value(node=node) == 4


# ---------------------------------------------------------------------------
# Satellite: node deleted while a member is Online — the syncer orphan path
# and the repair/recovery path must compose without double-detach.
# ---------------------------------------------------------------------------

class TestNodeGoneOrphanCompose:
    def test_node_deletion_replaces_member_and_reclaims_orphan(self):
        store, pool, chaos, req_rec, res_rec = make_world(nodes=3)
        make_request(store)
        to_running(store, req_rec, res_rec)
        victim = next(c for c in members(store) if c.spec.worker_id == 1)
        victim_devices = set(victim.status.device_ids)
        gone_node = victim.spec.target_node
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=0.05,
                                vanish_threshold=2)

        store.delete(Node, gone_node)

        # Drive controllers + syncer together until the request is whole
        # again AND the orphaned fabric attachment is reclaimed.
        import time as _time
        deadline = _time.monotonic() + 30
        t = 0.0
        req = None
        while _time.monotonic() < deadline:
            try:
                req_rec.reconcile("req-1")
            except FabricError:
                pass
            for c in store.list(ComposableResource):
                try:
                    res_rec.reconcile(c.metadata.name)
                except FabricError:
                    pass
            t += 0.1
            syncer.sync_once(now=t)
            req = store.get(ComposabilityRequest, "req-1")
            live = members(store)
            attached_ids = {d.device_id for d in pool.get_resources()}
            no_duplicate_attachments(pool)
            if (
                req.status.state == REQUEST_STATE_RUNNING
                and len(live) == 2
                and all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
                and not (victim_devices & attached_ids)
            ):
                break
        else:
            raise AssertionError(
                f"never converged: req={req.status.to_dict()},"
                f" fabric={[d.device_id for d in pool.get_resources()]}"
            )
        # Replacement landed off the dead node; orphaned chips returned to
        # the pool exactly once (no double-detach: counts reconcile).
        live = members(store)
        assert all(c.spec.target_node != gone_node for c in live)
        attached = sum(len(c.status.device_ids) for c in live)
        assert pool.free_chips(MODEL) + attached + pool.dead_chips(MODEL) <= 64
        # Every chip is either free, attached to a live member, or still
        # carved into the slice reservation — nothing leaked or doubled.
        assert len({d.device_id for d in pool.get_resources()}) == attached
