"""Repair soak: the full operator under sustained post-Ready device death.

ISSUE-7 acceptance: with the production-shaped stack (informer cache ON,
fabric dispatcher ON), 100 attach/detach cycles at a 10% scripted
post-Ready device-death rate must all converge back to full Ready count —
every killed chip's member detected (damped health probes), replaced
make-before-break on healthy capacity, the failed member force-detached —
with zero double-attaches (nonce-checked via the durable pending_op
intents), the per-request surge budget never exceeded, and the fleet-level
repair breaker verifiably freezing repairs in a >50%-degraded brownout
instead of mass-detaching.

Marked slow+repair: excluded from tier-1 (`-m 'not slow'`); run with
`make repair-soak` or `pytest -m repair`.
"""

from __future__ import annotations

import random
import time

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import (
    REQUEST_STATE_RUNNING,
    RESOURCE_STATE_DEGRADED,
    RESOURCE_STATE_ONLINE,
    RESOURCE_STATE_REPAIRING,
)
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
    RepairConfig,
    RequestTiming,
)
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.fabric.chaos import ChaosFabricProvider
from tpu_composer.fabric.dispatcher import FabricDispatcher
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.cache import CachedClient
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.metrics import repair_breaker_open, repairs_total
from tpu_composer.runtime.store import Store

from test_crash_restart import RecordingPool, assert_no_double_attach

CYCLES = 100
DEATH_RATE = 0.10
SEED = 20260803
MODEL = "tpu-v4"


def build_operator(store, pool, chaos, *, breaker=None):
    """Production-shaped stack: cache ON, dispatcher ON (the acceptance
    configuration), repair-tuned sub-second timing."""
    client = CachedClient(store)
    dispatcher = FabricDispatcher(chaos, batch_window=0.01, concurrency=4,
                                  poll_interval=0.02)
    agent = FakeNodeAgent(pool=pool)
    mgr = Manager(store=client, dispatcher=dispatcher, drain_timeout=2.0,
                  health_addr=None)
    mgr.add_controller(ComposabilityRequestReconciler(
        client, chaos,
        timing=RequestTiming(updating_poll=0.05, cleaning_poll=0.02,
                             running_poll=0.5, repair_poll=0.05),
        repair=breaker or RepairConfig(),
    ))
    mgr.add_controller(ComposableResourceReconciler(
        client, chaos, agent,
        timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.02,
                              detach_poll=0.05, detach_fast=0.02,
                              busy_poll=0.05, health_poll=0.05,
                              degraded_poll=0.05,
                              health_failure_threshold=2,
                              health_recovery_threshold=1),
        dispatcher=dispatcher))
    # Wide grace: a repair's detach window must never false-positive as an
    # orphan; vanish detection stays at its damped default.
    mgr.add_runnable(UpstreamSyncer(client, chaos, period=0.1, grace=5.0))
    mgr.add_runnable(dispatcher.run)
    mgr.start(workers_per_controller=2)
    return mgr, client


def live_members(store, owner):
    return [
        c for c in store.list(ComposableResource)
        if not c.being_deleted
        and c.metadata.labels.get("app.kubernetes.io/managed-by") == owner
    ]


def request_converged(store, name):
    req = store.try_get(ComposabilityRequest, name)
    if req is None or req.status.state != REQUEST_STATE_RUNNING:
        return False
    live = live_members(store, name)
    return (
        len(live) == req.status.slice.num_hosts
        and all(c.status.state == RESOURCE_STATE_ONLINE for c in live)
    )


@pytest.mark.slow
@pytest.mark.repair
def test_100_cycles_with_10pct_post_ready_device_death():
    store = Store()
    for i in range(6):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = RecordingPool(chips={MODEL: 64})
    chaos = ChaosFabricProvider(pool)
    mgr, client = build_operator(store, pool, chaos)
    rng = random.Random(SEED)

    fails: list = []
    kills = 0
    max_repairing = 0

    def wait(cond, what, deadline_s=60, track_surge=False):
        nonlocal max_repairing
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if track_surge:
                repairing = [
                    c for c in store.list(ComposableResource)
                    if c.status.state == RESOURCE_STATE_REPAIRING
                ]
                max_repairing = max(max_repairing, len(repairing))
                if len(repairing) > 1:
                    fails.append(
                        f"surge budget exceeded: {[c.name for c in repairing]}"
                    )
                    return False
            if cond():
                return True
            time.sleep(0.01)
        fails.append(what)
        return False

    try:
        for i in range(CYCLES):
            name = f"repair-{i}"
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name=name),
                spec=ComposabilityRequestSpec(
                    resource=ResourceDetails(type="tpu", model=MODEL, size=8),
                    max_concurrent_repairs=1,
                ),
            ))
            if not wait(lambda: request_converged(store, name),
                        f"{name}: never Running"):
                break
            if rng.random() < DEATH_RATE:
                kills += 1
                victim = rng.choice(live_members(store, name))
                dead = rng.choice(victim.status.device_ids)
                pool.kill_device(dead)

                def healed():
                    if not request_converged(store, name):
                        return False
                    attached = {d.device_id for d in pool.get_resources()}
                    return dead not in attached and not any(
                        c.being_deleted for c in store.list(ComposableResource)
                    )

                if not wait(healed, f"{name}: never healed after losing"
                            f" {dead}", track_surge=True):
                    break
            store.delete(ComposabilityRequest, name)
            if not wait(lambda: store.try_get(ComposabilityRequest, name)
                        is None, f"{name}: teardown never completed"):
                break
        # Settle: in-flight detaches + syncer reclaim.
        wait(
            lambda: (
                not store.list(ComposableResource)
                and pool.get_resources() == []
            ),
            "fleet never drained at end of soak", deadline_s=30,
        )
    finally:
        mgr.stop()

    assert not fails, fails[:10]
    assert kills >= 5, f"only {kills} scripted deaths — soak proved nothing"
    # Zero double-attaches, nonce-checked against the durable intents.
    assert_no_double_attach(pool.events)
    # Surge budget respected AND repairs actually exercised concurrently.
    assert max_repairing == 1, max_repairing
    assert repairs_total.value(outcome="replaced") >= kills * 0.8
    # Inventory reconciles: every chip is free or retired to the graveyard.
    assert pool.free_chips(MODEL) + pool.dead_chips(MODEL) == 64
    assert pool.dead_chips(MODEL) == kills
    leftovers = [k for k in store.keys()
                 if k[0] in ("ComposabilityRequest", "ComposableResource")]
    assert leftovers == [], leftovers[:10]


@pytest.mark.slow
@pytest.mark.repair
def test_brownout_freezes_repairs_fleet_wide():
    """>50% of attached members degrade at once: the repair breaker must
    freeze repairs — zero detaches — and the fleet must recover in place
    when the brownout lifts."""
    store = Store()
    for i in range(4):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = RecordingPool(chips={MODEL: 64})
    chaos = ChaosFabricProvider(pool)
    # Freeze above 1/4 degraded; the drain grace (3 s, below) is wider than
    # the whole detection window, so even a repair that legitimately slips
    # in before the fraction crosses the threshold cannot DETACH anything
    # before the breaker opens — "no detaches while frozen" is exact.
    mgr, client = build_operator(
        store, pool, chaos,
        breaker=RepairConfig(breaker_fraction=0.25, breaker_min_members=2,
                             min_degraded_seconds=2.0),
    )
    try:
        for i in range(4):
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name=f"req-{i}"),
                spec=ComposabilityRequestSpec(
                    resource=ResourceDetails(type="tpu", model=MODEL, size=4),
                    repair_grace_seconds=3.0,
                ),
            ))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if all(request_converged(store, f"req-{i}") for i in range(4)):
                break
            time.sleep(0.01)
        else:
            raise AssertionError("fleet never reached Ready")
        members_before = {
            c.name for c in store.list(ComposableResource)
        }
        # Brownout: every node goes dark at once (fabric still answers —
        # with bad news everywhere).
        for n in store.list(Node):
            chaos.degrade_node(n.metadata.name)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if repair_breaker_open.value() == 1.0:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("repair breaker never opened")
        # Hold the brownout: no member may be detached — the original
        # fleet only ever GROWS (a pre-freeze repair may have added a
        # replacement; it must never remove anyone while frozen).
        hold_until = time.monotonic() + 2.0
        while time.monotonic() < hold_until:
            current = store.list(ComposableResource)
            assert members_before <= {c.name for c in current}, (
                "breaker open but original members were detached"
                " (mass-detach!)"
            )
            assert not any(c.being_deleted for c in current)
            time.sleep(0.05)
        degraded = [
            c for c in store.list(ComposableResource)
            if c.status.state in (RESOURCE_STATE_DEGRADED,
                                  RESOURCE_STATE_REPAIRING)
        ]
        assert len(degraded) >= 3
        # Brownout lifts: the fleet converges back to full Ready. Members
        # whose repair never started recover IN PLACE; at most one member
        # (a pre-freeze repair) may have rotated.
        chaos.heal()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (
                all(request_converged(store, f"req-{i}") for i in range(4))
                and not any(
                    c.being_deleted for c in store.list(ComposableResource)
                )
            ):
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                "fleet never recovered after the brownout lifted"
            )
        survivors = {c.name for c in store.list(ComposableResource)}
        # With the dwell (2 s) wider than the whole degrade->recover window
        # no repair can act at all: every original member recovers in place.
        assert survivors == members_before, (
            f"members rotated through a brownout: before={members_before},"
            f" after={survivors}"
        )
        assert_no_double_attach(pool.events)
    finally:
        mgr.stop()
