"""ComposabilityRequest state machine + allocator, stepped one reconcile at a
time (reference pattern: composabilityrequest_controller_test.go table-driven
entries, SURVEY.md §4)."""

import pytest

from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    OtherSpec,
    ResourceDetails,
)
from tpu_composer.api.types import (
    ANNOTATION_DELETE_DEVICE,
    ANNOTATION_LAST_USED_TIME,
    LABEL_MANAGED_BY,
    REQUEST_STATE_CLEANING,
    REQUEST_STATE_NODE_ALLOCATING,
    REQUEST_STATE_RUNNING,
    REQUEST_STATE_UPDATING,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers.request_controller import (
    AllocationError,
    ComposabilityRequestReconciler,
)
from tpu_composer.controllers.resource_controller import ComposableResourceReconciler
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.store import Store


@pytest.fixture()
def world():
    store = Store()
    for i in range(8):  # mirrors the reference suite's worker-0..7 fixture
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 4
        n.status.milli_cpu = 8000
        n.status.memory = 64 << 30
        n.status.allowed_pod_number = 100
        store.create(n)
    pool = InMemoryPool()
    agent = FakeNodeAgent(pool=pool)
    req_rec = ComposabilityRequestReconciler(store, pool)
    res_rec = ComposableResourceReconciler(store, pool, agent)
    return store, pool, agent, req_rec, res_rec


def make_request(store, name="req-1", type_="tpu", model="tpu-v4", size=4, **kw):
    req = ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(
            resource=ResourceDetails(type=type_, model=model, size=size, **kw)
        ),
    )
    return store.create(req)


def get_req(store, name="req-1"):
    return store.get(ComposabilityRequest, name)


def children_of(store, name="req-1"):
    return store.list(ComposableResource, label_selector={LABEL_MANAGED_BY: name})


def run_to_ready(store, req_rec, res_rec, name="req-1", max_steps=60):
    """Pump both reconcilers until the request is Running (or give up)."""
    for _ in range(max_steps):
        req_rec.reconcile(name)
        for c in store.list(ComposableResource):
            res_rec.reconcile(c.metadata.name)
        if get_req(store, name).status.state == REQUEST_STATE_RUNNING:
            return
    raise AssertionError(
        f"request never reached Running: {get_req(store, name).status.to_dict()}"
    )


class TestTpuAllocation:
    def test_single_host_slice_to_running(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4)
        req_rec.reconcile("req-1")  # "" -> NodeAllocating
        req_rec.reconcile("req-1")  # allocate -> Updating
        req = get_req(store)
        assert req.status.state == REQUEST_STATE_UPDATING
        assert req.status.slice.topology == "1x2x2"
        assert req.status.slice.num_hosts == 1
        assert len(req.status.resources) == 1
        run_to_ready(store, req_rec, res_rec)
        req = get_req(store)
        assert req.status.state == REQUEST_STATE_RUNNING
        (rs,) = req.status.resources.values()
        assert rs.state == RESOURCE_STATE_ONLINE
        assert len(rs.device_ids) == 4

    def test_multi_host_pod_slice(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=32)
        run_to_ready(store, req_rec, res_rec)
        req = get_req(store)
        assert req.status.slice.num_hosts == 8
        assert len(req.status.slice.worker_hostnames) == 8
        assert len(set(req.status.slice.worker_hostnames)) == 8
        kids = children_of(store)
        assert len(kids) == 8
        assert sorted(c.spec.worker_id for c in kids) == list(range(8))
        assert all(c.spec.chip_count == 4 for c in kids)
        # 32 chips carved from the pool
        assert pool.free_chips("tpu-v4") == 64 - 32

    def test_all_or_nothing_when_pool_too_small(self, world):
        store, pool, agent, req_rec, res_rec = world
        small = InMemoryPool(chips={"tpu-v4": 6})
        req_rec = ComposabilityRequestReconciler(store, small)
        make_request(store, size=8)
        with pytest.raises(Exception):
            req_rec.reconcile("req-1")
        req = get_req(store)
        # The fused ""/allocating pass never persisted a transition.
        assert req.status.state == ""
        assert "free" in req.status.error
        assert small.free_chips("tpu-v4") == 6  # nothing leaked
        assert children_of(store) == []

    def test_not_enough_hosts_is_allocation_error(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=64)  # needs 16 hosts, we have 8
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")
        assert "hosts" in get_req(store).status.error

    def test_invalid_chip_count_surfaces_topology_error(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=6)
        with pytest.raises(Exception):
            req_rec.reconcile("req-1")
        assert "cannot form a slice" in get_req(store).status.error

    def test_target_node_single_host(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4, target_node="worker-3")
        run_to_ready(store, req_rec, res_rec)
        (child,) = children_of(store)
        assert child.spec.target_node == "worker-3"

    def test_target_node_rejects_multi_host_topology(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8, target_node="worker-0")
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")

    def test_default_policy_places_multi_host_slice(self, world):
        # For tpu the topology dictates host count; the default (samenode)
        # policy must not block a multi-host slice.
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8, allocation_policy="samenode")
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len({c.spec.target_node for c in kids}) == 2

    def test_topology_policy_spreads_multi_host(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8, allocation_policy="topology")
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len({c.spec.target_node for c in kids}) == 2

    def test_capacity_filter_respects_other_spec(self, world):
        store, pool, agent, req_rec, res_rec = world
        # Demand more CPU than any node offers.
        make_request(store, size=4, other_spec=OtherSpec(milli_cpu=99999))
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")

    def test_occupancy_excludes_full_nodes(self, world):
        store, pool, agent, req_rec, res_rec = world
        # Fill every node but worker-7 with a 4-chip slice each.
        for i in range(7):
            make_request(store, name=f"filler-{i}", size=4, target_node=f"worker-{i}")
            run_to_ready(store, req_rec, res_rec, name=f"filler-{i}")
        make_request(store, size=4)
        run_to_ready(store, req_rec, res_rec)
        (child,) = children_of(store)
        assert child.spec.target_node == "worker-7"


class TestScalarCompat:
    def test_gpu_request_to_running(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, type_="gpu", model="gpu-a100", size=2,
                     allocation_policy="differentnode")
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len(kids) == 2
        assert len({c.spec.target_node for c in kids}) == 2
        assert pool.free_chips("gpu-a100") == 6

    def test_samenode_packs_one_node(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, type_="gpu", model="gpu-a100", size=2)
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len({c.spec.target_node for c in kids}) == 1

    def test_shrink_uses_deletion_priorities(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, type_="gpu", model="gpu-a100", size=3,
                     allocation_policy="differentnode")
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        # Mark one child explicitly deletable and one as recently used.
        marked = kids[0]
        marked.metadata.annotations[ANNOTATION_DELETE_DEVICE] = "true"
        store.update(marked)
        used = kids[1]
        used.metadata.annotations[ANNOTATION_LAST_USED_TIME] = "2026-07-29T00:00:00Z"
        store.update(used)

        req = get_req(store)
        req.spec.resource.size = 2
        store.update(req)
        # Running sees drift -> NodeAllocating -> deletes the marked child.
        req_rec.reconcile("req-1")
        req_rec.reconcile("req-1")
        doomed = store.try_get(ComposableResource, marked.metadata.name)
        assert doomed is None or doomed.being_deleted
        survivor = store.get(ComposableResource, used.metadata.name)
        assert not survivor.being_deleted

    def test_grow_keeps_existing_children(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, type_="gpu", model="gpu-a100", size=1)
        run_to_ready(store, req_rec, res_rec)
        (orig,) = children_of(store)
        req = get_req(store)
        req.spec.resource.size = 2
        store.update(req)
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len(kids) == 2
        assert orig.metadata.name in {c.metadata.name for c in kids}


class TestLifecycle:
    def test_delete_cleans_children_and_releases_chips(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8)
        run_to_ready(store, req_rec, res_rec)
        assert pool.free_chips("tpu-v4") == 56
        store.delete(ComposabilityRequest, "req-1")
        for _ in range(30):
            if store.try_get(ComposabilityRequest, "req-1") is None:
                break
            req_rec.reconcile("req-1")
            for c in store.list(ComposableResource):
                res_rec.reconcile(c.metadata.name)
        assert store.try_get(ComposabilityRequest, "req-1") is None
        assert store.list(ComposableResource) == []
        assert pool.free_chips("tpu-v4") == 64  # slice fully released

    def test_spec_drift_in_running_reallocates(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4)
        run_to_ready(store, req_rec, res_rec)
        req = get_req(store)
        req.spec.resource.size = 8
        store.update(req)
        req_rec.reconcile("req-1")
        assert get_req(store).status.state == REQUEST_STATE_NODE_ALLOCATING
        run_to_ready(store, req_rec, res_rec)
        req = get_req(store)
        assert req.status.slice.num_hosts == 2
        assert sum(len(r.device_ids) for r in req.status.resources.values()) == 8

    def test_member_loss_triggers_reallocation(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8)
        run_to_ready(store, req_rec, res_rec)
        victim = children_of(store)[0]
        store.delete(ComposableResource, victim.metadata.name)
        # let the victim's detach run to purge
        for _ in range(10):
            if store.try_get(ComposableResource, victim.metadata.name) is None:
                break
            res_rec.reconcile(victim.metadata.name)
        req_rec.reconcile("req-1")
        assert get_req(store).status.state == REQUEST_STATE_NODE_ALLOCATING
        run_to_ready(store, req_rec, res_rec)
        assert get_req(store).status.state == REQUEST_STATE_RUNNING

    def test_request_gc_when_target_node_deleted(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4, target_node="worker-2")
        run_to_ready(store, req_rec, res_rec)
        store.delete(Node, "worker-2")
        req_rec.reconcile("req-1")
        req = get_req(store)
        assert req.being_deleted
        assert req.status.state == REQUEST_STATE_CLEANING

    def test_size_zero_runs_with_no_children(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=0)
        run_to_ready(store, req_rec, res_rec)
        assert children_of(store) == []


class TestScalarRecovery:
    def test_lost_scalar_child_is_replaced(self, world):
        """A gpu request that loses a child must re-allocate it, not flap
        Running<->Updating at reduced size."""
        store, pool, agent, req_rec, res_rec = world
        make_request(store, type_="gpu", model="gpu-a100", size=2,
                     allocation_policy="differentnode")
        run_to_ready(store, req_rec, res_rec)
        victim = children_of(store)[0]
        store.delete(ComposableResource, victim.metadata.name)
        for _ in range(10):
            if store.try_get(ComposableResource, victim.metadata.name) is None:
                break
            res_rec.reconcile(victim.metadata.name)
        run_to_ready(store, req_rec, res_rec)
        kids = children_of(store)
        assert len(kids) == 2
        assert all(c.status.state == RESOURCE_STATE_ONLINE for c in kids)

    def test_scalar_target_node_overcommit_rejected(self, world):
        store, pool, agent, req_rec, res_rec = world
        # worker-0 has 4 slots; ask for 5 devices pinned there.
        make_request(store, type_="gpu", model="gpu-a100", size=5,
                     target_node="worker-0")
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")
        assert "free device ports" in get_req(store).status.error


class TestLiveResize:
    """Live slice grow/shrink (SURVEY §7 M4, VERDICT r2 ask #3): when
    chips_per_host is unchanged and survivors form a stable worker prefix,
    resize keeps existing children alive — child UIDs, chips and TPU_*
    worker coordinates all survive. Reference contrast: device reuse on
    spec drift (composabilityrequest_controller.go:254-305); dissolve is
    reserved for incompatible reshapes."""

    def test_grow_keeps_existing_children(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4)  # v4: one host tray, 1x2x2
        run_to_ready(store, req_rec, res_rec)
        orig = children_of(store)
        assert len(orig) == 1
        orig_uid = orig[0].metadata.uid
        orig_devices = list(orig[0].status.device_ids)
        orig_node = orig[0].spec.target_node

        req = get_req(store)
        req.spec.resource.size = 8  # -> 2x2x2, two hosts
        store.update(req)
        run_to_ready(store, req_rec, res_rec)

        kids = sorted(children_of(store), key=lambda c: c.spec.worker_id)
        assert len(kids) == 2
        survivor, added = kids
        # The original member was never deleted: same object, same chips.
        assert survivor.metadata.uid == orig_uid
        assert list(survivor.status.device_ids) == orig_devices
        assert survivor.spec.worker_id == 0
        assert survivor.spec.topology == "2x2x2"
        assert added.spec.worker_id == 1
        assert added.spec.target_node != orig_node
        sl = get_req(store).status.slice
        assert sl.num_hosts == 2 and sl.topology == "2x2x2"
        # Stable prefix: worker 0's hostname (already injected into pods
        # as TPU_WORKER_HOSTNAMES[0]) is unchanged.
        assert sl.worker_hostnames[0] == orig_node

    def test_shrink_keeps_surviving_prefix(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8)
        run_to_ready(store, req_rec, res_rec)
        kids = sorted(children_of(store), key=lambda c: c.spec.worker_id)
        keeper_uid = kids[0].metadata.uid
        keeper_devices = list(kids[0].status.device_ids)
        free_before = pool.free_chips("tpu-v4")

        req = get_req(store)
        req.spec.resource.size = 4
        store.update(req)
        run_to_ready(store, req_rec, res_rec)

        kids = children_of(store)
        assert len(kids) == 1
        assert kids[0].metadata.uid == keeper_uid
        assert list(kids[0].status.device_ids) == keeper_devices
        assert kids[0].spec.topology == "1x2x2"
        sl = get_req(store).status.slice
        assert sl.num_hosts == 1 and sl.topology == "1x2x2"
        # The dropped worker's chips went back to the pool.
        assert pool.free_chips("tpu-v4") == free_before + 4

    def test_chips_per_host_change_dissolves(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=2)  # standalone sub-host group: 2 chips
        run_to_ready(store, req_rec, res_rec)
        orig_uid = children_of(store)[0].metadata.uid

        req = get_req(store)
        req.spec.resource.size = 8  # chips_per_host 2 -> 4: no live path
        store.update(req)
        run_to_ready(store, req_rec, res_rec)

        kids = children_of(store)
        assert len(kids) == 2
        assert all(c.metadata.uid != orig_uid for c in kids)

    def test_grow_of_node_pinned_request_is_rejected(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4, target_node="worker-0")
        run_to_ready(store, req_rec, res_rec)
        req = get_req(store)
        req.spec.resource.size = 8  # needs 2 hosts; pin allows 1
        store.update(req)
        req_rec.reconcile("req-1")  # Running -> NodeAllocating
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")
        assert "single-host" in get_req(store).status.error


class TestDeletionRaces:
    """Request-side analogs of the BENCH_r03 race: objects purged between the
    reconciler's cache read and its write must mean "already done"
    (composabilityrequest_controller.go:153-157's IgnoreNotFound)."""

    def test_finalizer_put_races_concurrent_purge(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4)
        run_to_ready(store, req_rec, res_rec)
        store.delete(ComposabilityRequest, "req-1")
        # Cleaning: tear children down fully, reach Deleting.
        for _ in range(30):
            req = store.try_get(ComposabilityRequest, "req-1")
            if req is None or req.status.state == "Deleting":
                break
            req_rec.reconcile("req-1")
            for c in store.list(ComposableResource):
                res_rec.reconcile(c.metadata.name)
        stale = get_req(store)  # stale cache copy, finalizer still on it
        req_rec.reconcile("req-1")  # real pass purges
        assert store.try_get(ComposabilityRequest, "req-1") is None
        r = req_rec._handle_deleting(stale)  # replay with the stale copy
        assert r.requeue_after == 0

    def test_target_node_gc_races_finalizerless_purge(self, world):
        """A request that never got its finalizer (never reconciled) purges
        outright on the GC delete; the delete-then-get must not raise."""
        store, pool, agent, req_rec, res_rec = world
        req = make_request(store, size=4, target_node="worker-2")
        req = get_req(store)
        req.status.state = REQUEST_STATE_RUNNING
        store.update_status(req)
        store.delete(Node, "worker-2")
        req_rec.reconcile("req-1")
        assert store.try_get(ComposabilityRequest, "req-1") is None

    def test_delete_children_tolerates_gone_child(self, world):
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=8)
        run_to_ready(store, req_rec, res_rec)
        victims = children_of(store)
        # One child vanishes entirely before _delete_children gets to it.
        from tpu_composer.runtime.store import NotFoundError
        store.delete(ComposableResource, victims[0].metadata.name)
        gone = store.try_get(ComposableResource, victims[0].metadata.name)
        if gone is not None:
            gone.metadata.finalizers = []
            store.update(gone)
        req = get_req(store)
        req_rec._delete_children(req, victims)  # must not raise
        for v in victims[1:]:
            assert store.get(ComposableResource, v.metadata.name).being_deleted


class TestRetopologizeObservability:
    def test_conflict_is_logged_and_retried_not_swallowed(self, world, caplog):
        """A failed topology rewrite must be visible (VERDICT r3 weak #5):
        the conflict is logged, the child keeps its old topology, and the
        any()-drift check re-runs the rewrite on the next allocation pass."""
        import logging
        store, pool, agent, req_rec, res_rec = world
        make_request(store, size=4)
        run_to_ready(store, req_rec, res_rec)
        child = children_of(store)[0]
        stale = child.deepcopy()
        # Bump the server-side rv so the reconciler's copy is stale.
        store.update(child)
        orig_topology = child.spec.topology
        stale.spec.topology = ""  # force the rewrite branch
        with caplog.at_level(logging.INFO):
            req_rec._retopologize([stale], orig_topology)
        assert any("retopologize" in r.getMessage() for r in caplog.records)
        # Server copy untouched by the failed rewrite.
        assert store.get(
            ComposableResource, child.metadata.name
        ).spec.topology == orig_topology
