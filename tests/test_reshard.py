"""Live-resize reshard continuity (SURVEY §7 M4 / VERDICT r2 ask #3).

The operator's grow path keeps slice workers 0..k-1 alive and appends new
hosts; the workload follows by rebuilding its mesh and resharding the train
state. These tests pin the contract on the virtual 8-device CPU mesh: a
4-device training run resharded onto 8 devices mid-stream produces the SAME
next-step loss as the run that never resized — parameters, optimizer moments
and data order all survive the move.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpu_composer.models.transformer import ModelConfig
from tpu_composer.parallel import (
    TrainConfig,
    make_mesh,
    make_train_state,
    make_train_step,
)
from tpu_composer.parallel.train import reshard_train_state


@pytest.fixture(scope="module")
def tc():
    return TrainConfig(
        model=ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                          d_ff=128, max_seq=32, dtype=jnp.float32)
    )


def _batches(tc, n, batch=4, seq=32):
    key = jax.random.key(7)
    return [
        jax.random.randint(jax.random.fold_in(key, i), (batch, seq), 0,
                           tc.model.vocab_size)
        for i in range(n)
    ]


def _run(tc, mesh, state, tokens_list):
    step_fn, batch_sharding = make_train_step(tc, mesh)
    losses = []
    for tokens in tokens_list:
        state, metrics = step_fn(state, jax.device_put(tokens, batch_sharding))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_grow_4_to_8_is_loss_continuous(tc):
    devices = jax.devices()
    assert len(devices) >= 8
    mesh4 = make_mesh({"dp": 2, "sp": 1, "tp": 2}, devices=devices[:4])
    mesh8 = make_mesh({"dp": 2, "sp": 2, "tp": 2}, devices=devices[:8])
    batches = _batches(tc, 5)

    # Control: never resized.
    state_c = make_train_state(tc, jax.random.key(0), mesh4)
    state_c, losses_c = _run(tc, mesh4, state_c, batches)

    # Resized: 3 steps on 4 devices, grow, 2 more steps on 8.
    state_r = make_train_state(tc, jax.random.key(0), mesh4)
    state_r, losses_a = _run(tc, mesh4, state_r, batches[:3])
    state_r = reshard_train_state(tc, state_r, mesh8)
    # Every leaf actually lives on the grown mesh now.
    leaf = jax.tree.leaves(state_r["params"])[0]
    assert set(leaf.sharding.mesh.devices.flat) == set(devices[:8])
    state_r, losses_b = _run(tc, mesh8, state_r, batches[3:])

    resized = losses_a + losses_b
    assert resized == pytest.approx(losses_c, rel=2e-4), (
        f"loss diverged across reshard: {resized} vs {losses_c}"
    )
    # And training is actually progressing, not frozen.
    assert losses_c[-1] < losses_c[0]


def test_shrink_8_to_4_is_loss_continuous(tc):
    devices = jax.devices()
    mesh8 = make_mesh({"dp": 2, "sp": 2, "tp": 2}, devices=devices[:8])
    mesh4 = make_mesh({"dp": 2, "sp": 1, "tp": 2}, devices=devices[:4])
    batches = _batches(tc, 4)

    state_c = make_train_state(tc, jax.random.key(0), mesh8)
    state_c, losses_c = _run(tc, mesh8, state_c, batches)

    state_r = make_train_state(tc, jax.random.key(0), mesh8)
    state_r, losses_a = _run(tc, mesh8, state_r, batches[:2])
    state_r = reshard_train_state(tc, state_r, mesh4)
    state_r, losses_b = _run(tc, mesh4, state_r, batches[2:])

    assert losses_a + losses_b == pytest.approx(losses_c, rel=2e-4)


class TestOperatorResizeDrivesReshard:
    """VERDICT r4 ask #8: both halves existed — the operator's live slice
    grow (test_e2e_operator.py::test_live_resize_grows_slice) and
    loss-continuous resharding (above) — but nothing drove
    ``reshard_train_state`` FROM an operator resize event. Here the full
    threaded operator grows a request 4 -> 8 chips; a trainer-side watch on
    the request observes the slice change and reshards the live train state
    onto the grown mesh; the next losses must match the never-resized run
    bit-for-bit (to tolerance)."""

    def test_grow_event_reshards_live_training(self, tc):
        from tpu_composer.agent.fake import FakeNodeAgent
        from tpu_composer.api import (
            ComposabilityRequest,
            ComposabilityRequestSpec,
            Node,
            ObjectMeta,
            ResourceDetails,
        )
        from tpu_composer.api.types import REQUEST_STATE_RUNNING
        from tpu_composer.controllers import (
            ComposabilityRequestReconciler,
            ComposableResourceReconciler,
            RequestTiming,
            ResourceTiming,
        )
        from tpu_composer.fabric.inmem import InMemoryPool
        from tpu_composer.runtime.manager import Manager
        from tpu_composer.runtime.store import Store

        import time as _time

        devices = jax.devices()
        assert len(devices) >= 8
        store = Store()
        for i in range(8):
            n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
            n.status.tpu_slots = 4
            store.create(n)
        pool = InMemoryPool()
        mgr = Manager(store=store)
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool,
            timing=RequestTiming(updating_poll=0.02, cleaning_poll=0.02)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(attach_poll=0.02, visibility_poll=0.02,
                                  detach_poll=0.02, detach_fast=0.02,
                                  busy_poll=0.02)))
        mgr.start(workers_per_controller=2)
        try:
            def slice_chips(req):
                s = req.status.slice
                return s.num_hosts * s.chips_per_host

            def wait_running_with(chips, timeout=20.0):
                deadline = _time.monotonic() + timeout
                while _time.monotonic() < deadline:
                    req = store.try_get(ComposabilityRequest, "train-job")
                    if (req is not None
                            and req.status.state == REQUEST_STATE_RUNNING
                            and slice_chips(req) == chips):
                        return req
                    _time.sleep(0.02)
                raise AssertionError(
                    f"never Running with {chips} chips: "
                    f"{store.get(ComposabilityRequest, 'train-job').status.to_dict()}"
                )

            # Trainer subscribes BEFORE the resize so the grow arrives as
            # watch events, not a poll.
            q = store.watch("ComposabilityRequest")

            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name="train-job"),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4)),
            ))
            wait_running_with(4)

            # Control: the run that never resizes (4 devices throughout).
            mesh4 = make_mesh({"dp": 2, "sp": 1, "tp": 2},
                              devices=devices[:4])
            batches = _batches(tc, 5)
            state_c = make_train_state(tc, jax.random.key(0), mesh4)
            state_c, losses_c = _run(tc, mesh4, state_c, batches)

            # Live run: 3 steps on the 4-chip slice...
            state_r = make_train_state(tc, jax.random.key(0), mesh4)
            state_r, losses_a = _run(tc, mesh4, state_r, batches[:3])

            # ...the user grows the request; the operator reconciles...
            req = store.get(ComposabilityRequest, "train-job")
            req.spec.resource.size = 8
            store.update(req)
            wait_running_with(8)

            # ...and the trainer's WATCH (not a poll) observes the grown
            # slice and reshards the live state onto the new mesh.
            resharded = False
            deadline = _time.monotonic() + 20
            while _time.monotonic() < deadline:
                evt = q.get(timeout=5)
                if (evt.obj.metadata.name == "train-job"
                        and evt.type != "DELETED"
                        and evt.obj.status.state == REQUEST_STATE_RUNNING
                        and slice_chips(evt.obj) == 8):
                    n_chips = slice_chips(evt.obj)
                    mesh8 = make_mesh({"dp": 2, "sp": 2, "tp": 2},
                                      devices=devices[:n_chips])
                    state_r = reshard_train_state(tc, state_r, mesh8)
                    resharded = True
                    break
            assert resharded, "watch never delivered the grown slice"
            leaf = jax.tree.leaves(state_r["params"])[0]
            assert set(leaf.sharding.mesh.devices.flat) == set(devices[:8])

            state_r, losses_b = _run(tc, mesh8, state_r, batches[3:])
            resized = losses_a + losses_b
            assert resized == pytest.approx(losses_c, rel=2e-4), (
                f"loss diverged across operator-driven reshard: "
                f"{resized} vs {losses_c}"
            )
        finally:
            mgr.stop()
