"""Live-resize reshard continuity (SURVEY §7 M4 / VERDICT r2 ask #3).

The operator's grow path keeps slice workers 0..k-1 alive and appends new
hosts; the workload follows by rebuilding its mesh and resharding the train
state. These tests pin the contract on the virtual 8-device CPU mesh: a
4-device training run resharded onto 8 devices mid-stream produces the SAME
next-step loss as the run that never resized — parameters, optimizer moments
and data order all survive the move.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from tpu_composer.models.transformer import ModelConfig
from tpu_composer.parallel import (
    TrainConfig,
    make_mesh,
    make_train_state,
    make_train_step,
)
from tpu_composer.parallel.train import reshard_train_state


@pytest.fixture(scope="module")
def tc():
    return TrainConfig(
        model=ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                          d_ff=128, max_seq=32, dtype=jnp.float32)
    )


def _batches(tc, n, batch=4, seq=32):
    key = jax.random.key(7)
    return [
        jax.random.randint(jax.random.fold_in(key, i), (batch, seq), 0,
                           tc.model.vocab_size)
        for i in range(n)
    ]


def _run(tc, mesh, state, tokens_list):
    step_fn, batch_sharding = make_train_step(tc, mesh)
    losses = []
    for tokens in tokens_list:
        state, metrics = step_fn(state, jax.device_put(tokens, batch_sharding))
        losses.append(float(metrics["loss"]))
    return state, losses


def test_grow_4_to_8_is_loss_continuous(tc):
    devices = jax.devices()
    assert len(devices) >= 8
    mesh4 = make_mesh({"dp": 2, "sp": 1, "tp": 2}, devices=devices[:4])
    mesh8 = make_mesh({"dp": 2, "sp": 2, "tp": 2}, devices=devices[:8])
    batches = _batches(tc, 5)

    # Control: never resized.
    state_c = make_train_state(tc, jax.random.key(0), mesh4)
    state_c, losses_c = _run(tc, mesh4, state_c, batches)

    # Resized: 3 steps on 4 devices, grow, 2 more steps on 8.
    state_r = make_train_state(tc, jax.random.key(0), mesh4)
    state_r, losses_a = _run(tc, mesh4, state_r, batches[:3])
    state_r = reshard_train_state(tc, state_r, mesh8)
    # Every leaf actually lives on the grown mesh now.
    leaf = jax.tree.leaves(state_r["params"])[0]
    assert set(leaf.sharding.mesh.devices.flat) == set(devices[:8])
    state_r, losses_b = _run(tc, mesh8, state_r, batches[3:])

    resized = losses_a + losses_b
    assert resized == pytest.approx(losses_c, rel=2e-4), (
        f"loss diverged across reshard: {resized} vs {losses_c}"
    )
    # And training is actually progressing, not frozen.
    assert losses_c[-1] < losses_c[0]


def test_shrink_8_to_4_is_loss_continuous(tc):
    devices = jax.devices()
    mesh8 = make_mesh({"dp": 2, "sp": 2, "tp": 2}, devices=devices[:8])
    mesh4 = make_mesh({"dp": 2, "sp": 1, "tp": 2}, devices=devices[:4])
    batches = _batches(tc, 4)

    state_c = make_train_state(tc, jax.random.key(0), mesh8)
    state_c, losses_c = _run(tc, mesh8, state_c, batches)

    state_r = make_train_state(tc, jax.random.key(0), mesh8)
    state_r, losses_a = _run(tc, mesh8, state_r, batches[:2])
    state_r = reshard_train_state(tc, state_r, mesh4)
    state_r, losses_b = _run(tc, mesh4, state_r, batches[2:])

    assert losses_a + losses_b == pytest.approx(losses_c, rel=2e-4)
