"""Fabric resilience layer: error taxonomy, circuit breaker, attach budgets,
quarantine + automatic reallocation (docs/RESILIENCE.md).

The tier-1 acceptance spine lives here: persistent injected attach failures
on one host trip that host's breaker, exhaust the resource's attach budget,
quarantine the node, and the owning ComposabilityRequest STILL reaches
Running by reallocating onto healthy capacity — with the breaker/quarantine
metrics visible in Registry.expose_text(). The long soaks are in
test_chaos_soak.py (marked slow/chaos); everything here runs in tier-1
under JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import random

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.publisher import (
    DevicePublisher,
    node_quarantine_name,
    node_quarantined,
)
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    ComposableResourceSpec,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.dra import DeviceTaintRule
from tpu_composer.api.types import (
    REQUEST_STATE_RUNNING,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.controllers.request_controller import (
    AllocationError,
    ComposabilityRequestReconciler,
)
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.fabric.breaker import (
    BreakerConfig,
    BreakerFabricProvider,
    BreakerOpenError,
    CircuitBreaker,
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
)
from tpu_composer.fabric.chaos import ChaosFabricProvider
from tpu_composer.fabric.httpx import (
    HttpStatusError,
    JsonHttpClient,
    TransientHttpStatusError,
    fabric_timeout,
)
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import (
    FabricError,
    TransientFabricError,
    WaitingDeviceAttaching,
    classify_fabric_error,
)
from tpu_composer.runtime.metrics import (
    fabric_breaker_trips_total,
    global_registry,
    resources_quarantined_total,
)
from tpu_composer.runtime.queue import RateLimitingQueue
from tpu_composer.runtime.store import Store


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


class MidpointRng:
    """random() == 0.5 — makes the breaker's ±20% reset jitter exact."""

    def random(self) -> float:
        return 0.5

    def uniform(self, a: float, b: float) -> float:
        return (a + b) / 2


# ---------------------------------------------------------------------------
# Error taxonomy (fabric/provider.py + fabric/httpx.py)
# ---------------------------------------------------------------------------

class TestTaxonomy:
    def test_transient_is_fabric_error(self):
        assert issubclass(TransientFabricError, FabricError)
        assert issubclass(BreakerOpenError, TransientFabricError)
        assert issubclass(TransientHttpStatusError, HttpStatusError)
        assert issubclass(TransientHttpStatusError, TransientFabricError)

    def test_classify_preserves_transience(self):
        t = classify_fabric_error(TransientFabricError("x"), "attach r0: x")
        assert isinstance(t, TransientFabricError)
        p = classify_fabric_error(FabricError("x"), "attach r0: x")
        assert isinstance(p, FabricError) and not isinstance(p, TransientFabricError)

    def test_connection_refused_is_typed_transient(self):
        # Nothing listens on this port: urllib's URLError must surface as a
        # typed TransientFabricError, never a raw urllib exception.
        client = JsonHttpClient("http://127.0.0.1:9", get_retries=0, timeout=0.5)
        with pytest.raises(TransientFabricError):
            client.request("PUT", "/v1/x", {})

    def test_5xx_transient_4xx_terminal(self):
        from tests.fake_fabric import FakeFabricServer

        srv = FakeFabricServer()
        try:
            client = JsonHttpClient(srv.url, get_retries=0)
            srv.fail_next("GET", "/v1/attachments", 503)
            with pytest.raises(TransientFabricError):
                client.request("GET", "/v1/attachments")
            srv.fail_next("GET", "/v1/attachments", 400)
            with pytest.raises(HttpStatusError) as ei:
                client.request("GET", "/v1/attachments")
            assert not isinstance(ei.value, TransientFabricError)
        finally:
            srv.close()

    def test_idempotent_get_retried_with_jitter(self):
        from tests.fake_fabric import FakeFabricServer

        srv = FakeFabricServer()
        sleeps = []
        try:
            client = JsonHttpClient(
                srv.url, get_retries=2, _sleep=sleeps.append,
                _rng=random.Random(3),
            )
            srv.fail_next("GET", "/v1/attachments", 502)
            status, payload = client.request("GET", "/v1/attachments")
            assert status == 200 and payload == {"attachments": []}
            assert len(sleeps) == 1 and sleeps[0] > 0
        finally:
            srv.close()

    def test_mutating_verbs_never_retried(self):
        from tests.fake_fabric import FakeFabricServer

        srv = FakeFabricServer()
        try:
            client = JsonHttpClient(srv.url, get_retries=2, _sleep=lambda s: None)
            srv.fail_next("PUT", "/v1/slices", 502)
            with pytest.raises(TransientFabricError):
                client.request("PUT", "/v1/slices/s1",
                               {"model": "tpu-v4", "topology": "2x2x1",
                                "nodes": ["w0"]})
            # The single 502 was consumed by the one (unretried) attempt.
            assert sum(1 for r in srv.request_log if r.startswith("PUT")) == 1
        finally:
            srv.close()

    def test_malformed_response_is_typed_transient(self):
        """A dying proxy/LB answering with a garbage status line raises
        http.client.BadStatusLine — it must surface as a typed transient
        (endpoint-reachability) fault, not leak raw or read as 'the
        endpoint answered' to the breaker."""
        import socket as socketlib
        import threading

        srv = socketlib.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def garbage_server():
            conn, _ = srv.accept()
            conn.recv(4096)
            conn.sendall(b"this is not http\r\n\r\n")
            conn.close()

        t = threading.Thread(target=garbage_server, daemon=True)
        t.start()
        try:
            client = JsonHttpClient(
                f"http://127.0.0.1:{port}", get_retries=0, timeout=5)
            with pytest.raises(TransientFabricError):
                client.request("PUT", "/v1/x", {})
        finally:
            t.join(timeout=5)
            srv.close()

    def test_timeout_env_override(self, monkeypatch):
        monkeypatch.setenv("TPU_COMPOSER_FABRIC_TIMEOUT", "7.5")
        assert fabric_timeout(60.0) == 7.5
        monkeypatch.setenv("TPU_COMPOSER_FABRIC_TIMEOUT", "bogus")
        assert fabric_timeout(60.0) == 60.0
        monkeypatch.delenv("TPU_COMPOSER_FABRIC_TIMEOUT")
        assert fabric_timeout(60.0) == 60.0

    def test_timeout_env_reaches_all_backends(self, monkeypatch):
        from tpu_composer.fabric.layout import LayoutApplyClient
        from tpu_composer.fabric.redfish import RedfishClient
        from tpu_composer.fabric.rest import RestPoolClient

        monkeypatch.setenv("TPU_COMPOSER_FABRIC_TIMEOUT", "3.25")
        monkeypatch.delenv("FABRIC_AUTH_URL", raising=False)
        for client in (
            RestPoolClient("http://x", token_cache=None),
            LayoutApplyClient("http://x", token_cache=None),
            RedfishClient("http://x", token_cache=None),
        ):
            assert client._http.timeout == 3.25
        # An explicit constructor timeout still wins over the env.
        assert RedfishClient("http://x", token_cache=None,
                             timeout=9.0)._http.timeout == 9.0


# ---------------------------------------------------------------------------
# Circuit breaker state machine
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def make(self, threshold=2, reset=10.0):
        clock = FakeClock()
        br = CircuitBreaker(
            "ep", "w0",
            BreakerConfig(failure_threshold=threshold, reset_timeout=reset),
            clock=clock, rng=MidpointRng(),
        )
        return br, clock

    def fail_once(self, br):
        br.acquire()
        br.failure()

    def test_trips_after_consecutive_failures(self):
        br, _ = self.make(threshold=3)
        for _ in range(2):
            self.fail_once(br)
        assert br.state == STATE_CLOSED
        self.fail_once(br)
        assert br.state == STATE_OPEN
        with pytest.raises(BreakerOpenError):
            br.acquire()

    def test_success_resets_streak(self):
        br, _ = self.make(threshold=2)
        self.fail_once(br)
        br.acquire()
        br.success()
        self.fail_once(br)
        assert br.state == STATE_CLOSED  # streak broken, never reached 2

    def test_half_open_probe_success_closes(self):
        br, clock = self.make(threshold=1, reset=10.0)
        self.fail_once(br)
        assert br.state == STATE_OPEN
        clock.t = 9.9  # MidpointRng -> open_until is exactly t+10
        with pytest.raises(BreakerOpenError):
            br.acquire()
        clock.t = 10.1
        br.acquire()
        assert br.state == STATE_HALF_OPEN
        # Only one probe admitted while its outcome is pending.
        with pytest.raises(BreakerOpenError):
            br.acquire()
        br.success()
        assert br.state == STATE_CLOSED

    def test_half_open_probe_failure_reopens(self):
        br, clock = self.make(threshold=1, reset=10.0)
        self.fail_once(br)
        trips_before = fabric_breaker_trips_total.value(endpoint="ep", scope="w0")
        clock.t = 10.1
        br.acquire()
        br.failure()
        assert br.state == STATE_OPEN
        assert fabric_breaker_trips_total.value(
            endpoint="ep", scope="w0"
        ) == trips_before + 1
        # A fresh reset window applies from the re-trip.
        clock.t = 15.0
        with pytest.raises(BreakerOpenError):
            br.acquire()

    def test_cancel_releases_probe_slot(self):
        br, clock = self.make(threshold=1, reset=10.0)
        self.fail_once(br)
        clock.t = 10.1
        br.acquire()
        br.cancel()  # the call never ran (sibling breaker rejected it)
        br.acquire()  # slot free again — no starvation
        br.success()
        assert br.state == STATE_CLOSED


class TestBreakerFabricProvider:
    def make_world(self, **cfg):
        pool = InMemoryPool(chips={"gpu-a100": 8})
        chaos = ChaosFabricProvider(pool)
        config = BreakerConfig(**{"failure_threshold": 2, "reset_timeout": 30.0,
                                  **cfg})
        fabric = BreakerFabricProvider(
            chaos, endpoint="mock-pool", config=config,
            clock=FakeClock(), rng=MidpointRng(),
        )
        return pool, chaos, fabric

    @staticmethod
    def gpu(name, node):
        return ComposableResource(
            metadata=ObjectMeta(name=name),
            spec=ComposableResourceSpec(type="gpu", model="gpu-a100",
                                        target_node=node),
        )

    def test_flaky_node_trips_only_its_own_breaker(self):
        pool, chaos, fabric = self.make_world()
        chaos.fail_node("w0")
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                fabric.add_resource(self.gpu("r0", "w0"))
        assert fabric.breaker("w0").state == STATE_OPEN
        assert fabric.breaker().state == STATE_CLOSED
        # w0 now fails FAST without touching the fabric...
        calls_before = chaos.calls
        with pytest.raises(BreakerOpenError):
            fabric.add_resource(self.gpu("r0", "w0"))
        assert chaos.calls == calls_before
        # ...while healthy nodes and endpoint-scoped verbs flow normally.
        assert fabric.add_resource(self.gpu("r1", "w1")).device_ids
        assert fabric.get_resources()

    def test_blackout_trips_endpoint_breaker(self):
        pool, chaos, fabric = self.make_world(
            failure_threshold=2, endpoint_failure_threshold=3)
        chaos.blackout()
        for _ in range(3):
            with pytest.raises(TransientFabricError):
                fabric.get_resources()
        assert fabric.breaker().state == STATE_OPEN
        calls_before = chaos.calls
        with pytest.raises(BreakerOpenError):
            fabric.get_resources()
        with pytest.raises(BreakerOpenError):
            fabric.add_resource(self.gpu("r0", "w9"))  # endpoint gate
        assert chaos.calls == calls_before

    def test_wait_sentinels_and_terminal_errors_do_not_trip(self):
        pool = InMemoryPool(chips={"gpu-a100": 1}, async_steps=3)
        fabric = BreakerFabricProvider(
            pool, endpoint="mock-pool",
            config=BreakerConfig(failure_threshold=1),
        )
        with pytest.raises(WaitingDeviceAttaching):
            fabric.add_resource(self.gpu("r0", "w0"))
        assert fabric.breaker("w0").state == STATE_CLOSED
        with pytest.raises(FabricError):  # terminal: unknown model
            fabric.add_resource(ComposableResource(
                metadata=ObjectMeta(name="r1"),
                spec=ComposableResourceSpec(type="gpu", model="nope",
                                            target_node="w0"),
            ))
        assert fabric.breaker("w0").state == STATE_CLOSED

    def test_forget_node_drops_breaker_and_metrics(self):
        from tpu_composer.runtime.metrics import fabric_breaker_state

        pool, chaos, fabric = self.make_world()
        chaos.fail_node("w0")
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                fabric.add_resource(self.gpu("r0", "w0"))
        assert "w0" in fabric._node_breakers
        key = (("endpoint", "mock-pool"), ("scope", "w0"))
        assert key in fabric_breaker_state._values
        fabric.forget_node("w0")
        assert "w0" not in fabric._node_breakers
        assert key not in fabric_breaker_state._values  # series retired
        # A recreated same-name node starts with a fresh closed breaker.
        assert fabric.breaker("w0").state == STATE_CLOSED

    def test_recovery_closes_after_reset_timeout(self):
        pool, chaos, fabric = self.make_world(reset_timeout=10.0)
        clock = fabric._clock
        chaos.fail_node("w0")
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                fabric.add_resource(self.gpu("r0", "w0"))
        chaos.heal_node("w0")
        with pytest.raises(BreakerOpenError):
            fabric.add_resource(self.gpu("r0", "w0"))
        clock.t = 10.1  # half-open probe passes through and closes
        assert fabric.add_resource(self.gpu("r0", "w0")).device_ids
        assert fabric.breaker("w0").state == STATE_CLOSED


# ---------------------------------------------------------------------------
# Chaos provider
# ---------------------------------------------------------------------------

class TestChaosProvider:
    def test_scripted_node_failures_then_heal(self):
        pool = InMemoryPool(chips={"gpu-a100": 4})
        chaos = ChaosFabricProvider(pool)
        res = TestBreakerFabricProvider.gpu("r0", "w0")
        chaos.fail_node("w0", times=2)
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                chaos.add_resource(res)
        assert chaos.add_resource(res).device_ids  # scripted count exhausted
        assert chaos.injected == 2

    def test_probabilistic_rate_is_seeded(self):
        pool = InMemoryPool(chips={"gpu-a100": 4})
        chaos = ChaosFabricProvider(pool, failure_rate=0.5, seed=42)
        outcomes = []
        for _ in range(50):
            try:
                chaos.get_resources()
                outcomes.append(True)
            except TransientFabricError:
                outcomes.append(False)
        assert 10 < sum(outcomes) < 40  # ~50% either way
        chaos2 = ChaosFabricProvider(InMemoryPool(), failure_rate=0.5, seed=42)
        outcomes2 = []
        for _ in range(50):
            try:
                chaos2.get_resources()
                outcomes2.append(True)
            except TransientFabricError:
                outcomes2.append(False)
        assert outcomes == outcomes2  # reproducible by seed

    def test_blackout_and_latency(self):
        sleeps = []
        pool = InMemoryPool(chips={"gpu-a100": 4})
        chaos = ChaosFabricProvider(pool, latency=0.25, sleep=sleeps.append)
        chaos.blackout()
        with pytest.raises(TransientFabricError):
            chaos.get_resources()
        chaos.heal()
        assert chaos.get_resources() == []
        assert sleeps == [0.25, 0.25]


# ---------------------------------------------------------------------------
# Attach budget + quarantine (resource controller)
# ---------------------------------------------------------------------------

def make_world(nodes=3, budget=3, breaker=None, chips=64):
    store = Store()
    for i in range(nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        n.status.milli_cpu = 8000
        n.status.memory = 64 << 30
        n.status.allowed_pod_number = 100
        store.create(n)
    pool = InMemoryPool(chips={"tpu-v4": chips})
    chaos = ChaosFabricProvider(pool)
    fabric = breaker(chaos) if breaker else chaos
    agent = FakeNodeAgent(pool=pool)
    req_rec = ComposabilityRequestReconciler(store, fabric)
    res_rec = ComposableResourceReconciler(
        store, fabric, agent, timing=ResourceTiming(attach_budget=budget)
    )
    return store, pool, chaos, fabric, req_rec, res_rec


def make_cr(store, pool, name="r0", node="worker-0"):
    pool.reserve_slice("s1", "tpu-v4", "2x2x1", [node])
    return store.create(ComposableResource(
        metadata=ObjectMeta(name=name),
        spec=ComposableResourceSpec(
            type="tpu", model="tpu-v4", target_node=node, chip_count=4,
            slice_name="s1", worker_id=0, topology="2x2x1",
        ),
    ))


def pump(store, req_rec, res_rec, name="req-1", steps=60,
         want_state=REQUEST_STATE_RUNNING):
    """Reconcile both controllers, absorbing the expected fabric errors the
    way the manager's worker loop does (backoff requeue)."""
    for _ in range(steps):
        try:
            req_rec.reconcile(name)
        except FabricError:
            pass
        for c in store.list(ComposableResource):
            try:
                res_rec.reconcile(c.metadata.name)
            except FabricError:
                pass
        req = store.get(ComposabilityRequest, name)
        if req.status.state == want_state:
            return req
    raise AssertionError(
        f"{name} never reached {want_state}:"
        f" {store.get(ComposabilityRequest, name).status.to_dict()}"
    )


class TestAttachBudget:
    def test_attempts_count_and_reset_on_success(self):
        store, pool, chaos, fabric, _, res_rec = make_world(budget=5)
        make_cr(store, pool)
        res_rec.reconcile("r0")  # "" -> Attaching
        chaos.fail_node("worker-0", times=2)
        for want in (1, 2):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
            assert res_rec._attach_streaks["r0"] == want
            cr = store.get(ComposableResource, "r0")
            # Persisted only when the error message changes (identical
            # repeat failures must NOT write status — a per-failure write
            # would self-trigger an immediate requeue and defeat backoff).
            assert cr.status.attach_attempts == 1
            assert cr.status.error
        res_rec.reconcile("r0")  # healed
        cr = store.get(ComposableResource, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert cr.status.attach_attempts == 0
        assert "r0" not in res_rec._attach_streaks
        assert not cr.status.quarantined

    def test_wait_sentinel_resets_attempt_streak(self):
        """A WaitingDeviceAttaching answer is evidence the fabric is serving
        this node: wire flakes sprinkled across a long async attach must not
        sum to a quarantine (the budget counts CONSECUTIVE failures)."""
        store, pool, chaos, fabric, _, res_rec = make_world(budget=3)
        pool._async_steps = 3  # CM-flavor: several waiting polls per attach
        make_cr(store, pool)
        res_rec.reconcile("r0")  # "" -> Attaching
        chaos.fail_node("worker-0", times=2)
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        assert res_rec._attach_streaks["r0"] == 2
        res_rec.reconcile("r0")  # healed -> waiting sentinel
        assert "r0" not in res_rec._attach_streaks
        assert store.get(ComposableResource, "r0").status.attach_attempts == 0
        # Two more flakes mid-wait still stay under the budget: no quarantine.
        chaos.fail_node("worker-0", times=2)
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        for _ in range(4):
            res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert not cr.status.quarantined

    def test_endpoint_outage_does_not_burn_node_budgets(self):
        """A dark fabric manager must NOT quarantine the fleet: endpoint-
        scoped breaker rejections carry no evidence against any node, so
        they bypass the attach budget entirely."""
        clock = FakeClock()
        store, pool, chaos, fabric, _, res_rec = make_world(
            budget=3,
            breaker=lambda inner: BreakerFabricProvider(
                inner, endpoint="mock-pool",
                config=BreakerConfig(failure_threshold=50,
                                     endpoint_failure_threshold=1,
                                     reset_timeout=60.0),
                clock=clock, rng=MidpointRng(),
            ),
        )
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.blackout()
        with pytest.raises(TransientFabricError):
            res_rec.reconcile("r0")  # real failure: trips endpoint breaker
        assert fabric.breaker().state == STATE_OPEN
        for _ in range(10):  # fail-fast rejections, NOT budget burn
            with pytest.raises(BreakerOpenError):
                res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert not cr.status.quarantined
        assert cr.status.attach_attempts == 1  # only the real failure counted
        assert not node_quarantined(store, "worker-0")
        # Fabric heals, breaker resets: the attach completes normally.
        chaos.heal()
        clock.t = 61.0
        res_rec.reconcile("r0")
        assert store.get(ComposableResource, "r0").status.state == RESOURCE_STATE_ONLINE

    def test_terminal_errors_do_not_burn_budget(self):
        store, pool, chaos, fabric, _, res_rec = make_world(budget=2)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        pool.inject_add_failure("r0", times=3)  # terminal FabricError
        for _ in range(3):
            with pytest.raises(FabricError):
                res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert cr.status.attach_attempts == 0
        assert not cr.status.quarantined

    def test_budget_exhaustion_quarantines(self):
        store, pool, chaos, fabric, _, res_rec = make_world(budget=3)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")  # persistent
        before = resources_quarantined_total.value(node="worker-0")
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        # Third failure hits the budget: no raise, durable quarantine.
        res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert cr.status.quarantined
        assert "quarantined" in cr.status.error
        assert node_quarantined(store, "worker-0")
        rule = store.get(DeviceTaintRule, node_quarantine_name("worker-0"))
        assert rule.spec.node_name == "worker-0"
        assert resources_quarantined_total.value(node="worker-0") == before + 1
        # Quarantined resources are inert — no more fabric calls.
        calls = chaos.calls
        res_rec.reconcile("r0")
        assert chaos.calls == calls

    def test_quarantined_resource_still_deletable(self):
        store, pool, chaos, fabric, _, res_rec = make_world(budget=1)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        res_rec.reconcile("r0")  # budget=1 -> immediate quarantine
        assert store.get(ComposableResource, "r0").status.quarantined
        store.delete(ComposableResource, "r0")
        for _ in range(4):
            if store.try_get(ComposableResource, "r0") is None:
                break
            res_rec.reconcile("r0")
        assert store.try_get(ComposableResource, "r0") is None

    def test_last_healthy_node_never_quarantined(self):
        """An endpoint-wide 5xx storm arrives node-attributed and marches
        through the fleet; the final healthy host must keep retrying
        (reference behavior) rather than quarantine 100% of capacity."""
        store, pool, chaos, fabric, _, res_rec = make_world(nodes=2, budget=2)
        DevicePublisher(store).quarantine_node("worker-1", "already down")
        make_cr(store, pool)  # worker-0: the last healthy host
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        for _ in range(5):  # well past the budget
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert not cr.status.quarantined
        assert not node_quarantined(store, "worker-0")
        assert "quarantine withheld" in cr.status.error
        # Capacity frees up (worker-1 repaired) -> the next exhausted
        # failure may quarantine after all.
        DevicePublisher(store).clear_node_quarantine("worker-1")
        res_rec.reconcile("r0")
        assert store.get(ComposableResource, "r0").status.quarantined

    def test_cordoned_peer_is_not_a_reallocation_target(self):
        """Quarantine eligibility uses the allocator's own gates: a peer
        that exists but is cordoned/NotReady cannot absorb replacement
        capacity, so quarantine must be withheld."""
        store, pool, chaos, fabric, _, res_rec = make_world(nodes=2, budget=2)
        peer = store.get(Node, "worker-1")
        peer.spec.unschedulable = True  # cordoned
        store.update(peer)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        for _ in range(4):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        assert not store.get(ComposableResource, "r0").status.quarantined
        assert not node_quarantined(store, "worker-0")

    def test_pinned_owner_never_quarantined_off_its_node(self):
        """A request pinned via target_node can never route elsewhere —
        quarantining its node would delete the pinned children and loop in
        AllocationError forever. It must keep retrying instead."""
        from tpu_composer.api.types import LABEL_MANAGED_BY

        store, pool, chaos, fabric, _, res_rec = make_world(nodes=3, budget=2)
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="req-pin"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v4", size=4,
                                         target_node="worker-0")),
        ))
        cr = make_cr(store, pool)
        cr.metadata.labels[LABEL_MANAGED_BY] = "req-pin"
        store.update(cr)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        for _ in range(4):  # well past the budget; healthy peers exist
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert not cr.status.quarantined
        assert not node_quarantined(store, "worker-0")
        assert "quarantine withheld" in cr.status.error

    def test_disabled_budget_never_quarantines(self):
        store, pool, chaos, fabric, _, res_rec = make_world(budget=0)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        for _ in range(10):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        cr = store.get(ComposableResource, "r0")
        assert not cr.status.quarantined
        assert res_rec._attach_streaks["r0"] == 10


class TestQuarantineAllocation:
    def test_allocator_skips_quarantined_nodes(self):
        store, pool, chaos, fabric, req_rec, res_rec = make_world()
        publisher = DevicePublisher(store)
        publisher.quarantine_node("worker-0", "test")
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="req-1"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v4", size=4)),
        ))
        req = pump(store, req_rec, res_rec)
        nodes = {rs.node_name for rs in req.status.resources.values()}
        assert "worker-0" not in nodes

    def test_pinned_request_on_quarantined_node_errors(self):
        store, pool, chaos, fabric, req_rec, res_rec = make_world()
        DevicePublisher(store).quarantine_node("worker-0", "test")
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="req-1"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v4", size=4,
                                         target_node="worker-0")),
        ))
        with pytest.raises(AllocationError, match="quarantined"):
            req_rec.reconcile("req-1")

    def test_node_deletion_clears_quarantine_and_breaker(self):
        """A recreated same-name node (autoscaled fleets reuse names) must
        not inherit a dead node's quarantine or breaker state."""
        from tpu_composer.runtime.store import WatchEvent

        clock = FakeClock()
        store, pool, chaos, fabric, req_rec, res_rec = make_world(
            budget=1,
            breaker=lambda inner: BreakerFabricProvider(
                inner, endpoint="mock-pool",
                config=BreakerConfig(failure_threshold=1, reset_timeout=300.0),
                clock=clock, rng=MidpointRng(),
            ),
        )
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        res_rec.reconcile("r0")  # budget=1 -> quarantine + tripped breaker
        assert node_quarantined(store, "worker-0")
        assert fabric.breaker("worker-0").state == STATE_OPEN

        node = store.get(Node, "worker-0")
        store.delete(Node, "worker-0")
        res_rec._map_node_event(WatchEvent(type="DELETED", obj=node))
        assert not node_quarantined(store, "worker-0")
        assert "worker-0" not in fabric._node_breakers
        # The reborn node starts fresh: closed breaker, allocatable.
        chaos.heal_node("worker-0")
        assert fabric.breaker("worker-0").state == STATE_CLOSED

    def test_mapper_cleanup_failure_still_gcs_and_clear_retries(self):
        """The node-DELETED mapper runs ONCE and the dispatch loop drops
        mapper exceptions: a wire fault during its quarantine clear must
        neither swallow the GC requeue keys nor strand the marker — the
        reconcile path (retried under backoff) re-runs the clear."""
        from tpu_composer.runtime.store import StoreError, WatchEvent

        store, pool, chaos, fabric, req_rec, res_rec = make_world(budget=1)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        res_rec.reconcile("r0")  # budget=1 -> quarantine
        assert node_quarantined(store, "worker-0")

        node = store.get(Node, "worker-0")
        store.delete(Node, "worker-0")
        orig_clear = res_rec.publisher.clear_node_quarantine
        res_rec.publisher.clear_node_quarantine = lambda n: (_ for _ in ()).throw(
            StoreError("apiserver unavailable")
        )
        keys = res_rec._map_node_event(WatchEvent(type="DELETED", obj=node))
        assert "r0" in keys  # GC requeues survive the failed cleanup
        assert node_quarantined(store, "worker-0")  # stranded... for now
        # The requeued reconcile GCs the resource AND retries the clear.
        res_rec.publisher.clear_node_quarantine = orig_clear
        res_rec.reconcile("r0")
        assert not node_quarantined(store, "worker-0")

    def test_clear_quarantine_restores_node(self):
        store, pool, chaos, fabric, req_rec, res_rec = make_world(nodes=1)
        pub = DevicePublisher(store)
        pub.quarantine_node("worker-0", "test")
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="req-1"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v4", size=4)),
        ))
        with pytest.raises(AllocationError):
            req_rec.reconcile("req-1")
        pub.clear_node_quarantine("worker-0")
        assert not pub.node_quarantined("worker-0")
        pump(store, req_rec, res_rec)


# ---------------------------------------------------------------------------
# The acceptance spine: breaker trip -> quarantine -> Ready via reallocation
# ---------------------------------------------------------------------------

class TestQuarantineReallocationE2E:
    def test_persistent_attach_failures_reroute_to_healthy_node(self):
        clock = FakeClock()
        store, pool, chaos, fabric, req_rec, res_rec = make_world(
            nodes=3, budget=4,
            breaker=lambda inner: BreakerFabricProvider(
                inner, endpoint="mock-pool",
                config=BreakerConfig(failure_threshold=2, reset_timeout=300.0),
                clock=clock, rng=MidpointRng(),
            ),
        )
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="req-1"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v4", size=4)),
        ))
        # Allocation is deterministic (tightest-fit, then name): the slice
        # lands on worker-0. Make its attach path persistently fail.
        chaos.fail_node("worker-0")
        trips_before = fabric_breaker_trips_total.value(
            endpoint="mock-pool", scope="worker-0")
        quarantined_before = resources_quarantined_total.value(node="worker-0")

        req = pump(store, req_rec, res_rec)

        # The request reached Ready on healthy capacity...
        assert req.status.state == REQUEST_STATE_RUNNING
        nodes = {rs.node_name for rs in req.status.resources.values()}
        assert nodes and "worker-0" not in nodes
        (placed,) = nodes
        assert len(pool.attached_to(placed)) == 4
        assert pool.attached_to("worker-0") == []
        # ...the flaky node's breaker tripped (2 real failures, then fail-fast
        # rejections burned the rest of the attach budget instantly)...
        assert fabric.breaker("worker-0").state == STATE_OPEN
        assert fabric.breaker().state == STATE_CLOSED
        assert fabric_breaker_trips_total.value(
            endpoint="mock-pool", scope="worker-0") == trips_before + 1
        # ...the device was quarantined, durably...
        assert node_quarantined(store, "worker-0")
        assert resources_quarantined_total.value(
            node="worker-0") == quarantined_before + 1
        # ...and every resilience metric is exposed for scrapes.
        text = global_registry.expose_text()
        for metric in ("fabric_breaker_state", "fabric_breaker_trips_total",
                       "resources_quarantined_total"):
            assert metric in text, metric

    def test_operator_restart_resumes_quarantine_state(self):
        """A controller restart must not grant the flaky node a fresh
        budget: the streak resumes from the last persisted floor in
        status.attach_attempts (written whenever the surfaced error
        changes), not from zero."""
        store, pool, chaos, fabric, req_rec, res_rec = make_world(budget=3)
        make_cr(store, pool)
        res_rec.reconcile("r0")
        chaos.fail_node("worker-0")
        for _ in range(2):
            with pytest.raises(TransientFabricError):
                res_rec.reconcile("r0")
        assert store.get(ComposableResource, "r0").status.attach_attempts >= 1
        # Restart: fresh reconciler over the same store resumes at >= 1.
        res_rec2 = ComposableResourceReconciler(
            store, fabric, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(attach_budget=3),
        )
        for _ in range(3):
            if store.get(ComposableResource, "r0").status.quarantined:
                break
            try:
                res_rec2.reconcile("r0")
            except TransientFabricError:
                pass
        assert store.get(ComposableResource, "r0").status.quarantined


# ---------------------------------------------------------------------------
# Syncer anti-drift under a full fabric outage (satellite)
# ---------------------------------------------------------------------------

class TestSyncerOutage:
    def make(self):
        store = Store()
        for i in range(2):
            n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
            n.status.tpu_slots = 8
            store.create(n)
        pool = InMemoryPool()
        chaos = ChaosFabricProvider(pool)
        return store, pool, chaos

    def test_stale_quarantine_marker_swept_when_node_gone(self):
        """Backstop for the node-DELETED mapper's one-shot cleanup: a
        quarantine marker whose node left the fleet — with NO dependent
        CRs left to retry the clear through — is cleared by the periodic
        sweep; live nodes keep their markers, per-device taints survive."""
        store, pool, chaos = self.make()
        pub = DevicePublisher(store)
        pub.quarantine_node("worker-0", "flaky fabric")  # node exists
        pub.quarantine_node("departed", "flaky fabric")  # node never/not in fleet
        pub.create_taints("worker-1", ["tpu-dev-1"], "bad chip")
        syncer = UpstreamSyncer(store, chaos, grace=100.0)
        syncer.sync_once(now=0.0)
        assert node_quarantined(store, "worker-0")  # live node: kept
        assert not node_quarantined(store, "departed")  # swept
        assert pub.tainted("tpu-dev-1")  # device taint untouched

    def test_sweep_runs_even_during_fabric_outage(self):
        """The sweep needs only the store: it must run BEFORE the fabric
        call so a dead fabric endpoint (get_resources raising every tick)
        can't suspend the stale-marker backstop for the whole outage."""
        store, pool, chaos = self.make()
        DevicePublisher(store).quarantine_node("departed", "stranded")
        chaos.blackout()
        syncer = UpstreamSyncer(store, chaos, grace=100.0)
        with pytest.raises(TransientFabricError):
            syncer.sync_once(now=0.0)
        assert not node_quarantined(store, "departed")  # swept anyway

    def test_outage_skips_sweep_without_wiping_state(self):
        store, pool, chaos = self.make()
        syncer = UpstreamSyncer(store, chaos, grace=100.0)
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        assert leaked in syncer.tracked_missing

        chaos.blackout()
        with pytest.raises(TransientFabricError):
            syncer.sync_once(now=50.0)
        # The failed sweep neither created detach-CRs nor dropped tracking.
        assert store.list(ComposableResource) == []
        assert leaked in syncer.tracked_missing

        chaos.heal()
        assert syncer.sync_once(now=150.0) == 1  # reconverged post-outage
        (cr,) = store.list(ComposableResource)
        assert cr.spec.force_detach

    def test_breaker_open_fails_sweep_fast_then_reconverges(self):
        store, pool, chaos = self.make()
        clock = FakeClock()
        fabric = BreakerFabricProvider(
            chaos, endpoint="mock-pool",
            config=BreakerConfig(failure_threshold=1, reset_timeout=30.0,
                                 endpoint_failure_threshold=1),
            clock=clock, rng=MidpointRng(),
        )
        syncer = UpstreamSyncer(store, fabric, grace=100.0)
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)

        chaos.blackout()
        with pytest.raises(TransientFabricError):
            syncer.sync_once(now=10.0)  # trips the endpoint breaker
        calls_before = chaos.calls
        with pytest.raises(BreakerOpenError):
            syncer.sync_once(now=20.0)  # fail-fast: fabric never touched
        assert chaos.calls == calls_before
        assert leaked in syncer.tracked_missing

        chaos.heal()
        clock.t = 31.0  # past reset: half-open probe goes through
        assert syncer.sync_once(now=150.0) == 1
        assert fabric.breaker().state == STATE_CLOSED

    def test_runnable_loop_survives_outage(self):
        """The manager-runnable entrypoint logs and keeps ticking (no
        unhandled exception kills the sweep thread)."""
        import threading

        store, pool, chaos = self.make()
        syncer = UpstreamSyncer(store, chaos, period=0.01, grace=0.02)
        chaos.blackout()
        stop = threading.Event()
        t = threading.Thread(target=syncer, args=(stop,))
        t.start()
        try:
            import time as _time

            _time.sleep(0.08)  # several failing sweeps
            pool.leak_attachment("worker-1", "tpu-v4")
            chaos.heal()
            deadline = _time.monotonic() + 5
            while _time.monotonic() < deadline:
                if store.list(ComposableResource):
                    break
                _time.sleep(0.01)
            assert store.list(ComposableResource)  # reconverged after heal
        finally:
            stop.set()
            t.join(timeout=5)


# ---------------------------------------------------------------------------
# Queue backoff jitter (satellite)
# ---------------------------------------------------------------------------

class TestQueueJitter:
    def test_backoff_is_jittered_and_bounded(self):
        rng = random.Random(7)
        q = RateLimitingQueue(base_delay=0.1, max_delay=5.0, jitter=rng)
        delays = []
        orig = q._push_delayed
        # capture the scheduling seam (backoff entries no longer route
        # through add_after — they carry a generation tag for forget())
        q._push_delayed = (  # type: ignore
            lambda key, delay, gen: delays.append(delay)
        )
        for _ in range(40):
            q.add_rate_limited("k")
        q._push_delayed = orig  # type: ignore
        assert all(0.1 <= d <= 5.0 for d in delays)
        assert max(delays) > 0.5  # it actually grows
        assert len(set(round(d, 6) for d in delays)) > 20  # not deterministic

    def test_two_keys_decorrelate(self):
        q = RateLimitingQueue(base_delay=0.1, max_delay=5.0,
                              jitter=random.Random(11))
        a, b = [], []
        orig = q._push_delayed
        q._push_delayed = (  # type: ignore
            lambda key, delay, gen: (a if key == "a" else b).append(delay)
        )
        for _ in range(6):
            q.add_rate_limited("a")
            q.add_rate_limited("b")
        q._push_delayed = orig  # type: ignore
        assert a != b  # lockstep herd broken

    def test_forget_resets_jitter_state(self):
        q = RateLimitingQueue(base_delay=0.1, max_delay=5.0,
                              jitter=random.Random(3))
        for _ in range(8):
            q.add_rate_limited("k")
        q.forget("k")
        assert q.retries("k") == 0
        assert q._last_delay.get("k") is None
