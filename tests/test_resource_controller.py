"""ComposableResource state machine, stepped one reconcile at a time —
the reference's test pattern (triggerComposableResourceReconcile,
composableresource_controller_test.go:90-102): drive Reconcile directly, then
assert the full status after each transition."""

import pytest

from tpu_composer.api import (
    ComposableResource,
    ComposableResourceSpec,
    Node,
    ObjectMeta,
)
from tpu_composer.api.types import (
    FINALIZER,
    LABEL_READY_TO_DETACH,
    RESOURCE_STATE_ATTACHING,
    RESOURCE_STATE_DELETING,
    RESOURCE_STATE_DETACHING,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import DeviceHealth, FabricError
from tpu_composer.runtime.store import Store


@pytest.fixture()
def world():
    """Store with nodes + mock fabric + fake agent + reconciler (not started:
    tests step reconcile() directly)."""
    store = Store()
    for i in range(4):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = InMemoryPool()
    agent = FakeNodeAgent(pool=pool)
    rec = ComposableResourceReconciler(store, pool, agent, timing=ResourceTiming())
    return store, pool, agent, rec


def make_tpu_cr(store, pool, name="r0", node="worker-0", slice_name="s1",
                worker_id=0, topology="2x2x1", reserve=True, nodes=None):
    if reserve:
        pool.reserve_slice(slice_name, "tpu-v4", topology, nodes or [node])
    cr = ComposableResource(
        metadata=ObjectMeta(name=name),
        spec=ComposableResourceSpec(
            type="tpu", model="tpu-v4", target_node=node, chip_count=4,
            slice_name=slice_name, worker_id=worker_id, topology=topology,
        ),
    )
    return store.create(cr)


def make_gpu_cr(store, name="g0", node="worker-0"):
    return store.create(ComposableResource(
        metadata=ObjectMeta(name=name),
        spec=ComposableResourceSpec(type="gpu", model="gpu-a100", target_node=node),
    ))


def step(rec, name):
    return rec.reconcile(name)


def get(store, name):
    return store.get(ComposableResource, name)


class TestAttachPath:
    def test_none_state_adds_finalizer_and_moves_to_attaching(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")
        cr = get(store, "r0")
        assert cr.has_finalizer(FINALIZER)
        assert cr.status.state == RESOURCE_STATE_ATTACHING

    def test_attaching_reaches_online_with_cdi_published(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")  # "" -> Attaching
        step(rec, "r0")  # Attaching -> Online
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert len(cr.status.device_ids) == 4
        assert "slice=s1" in cr.status.cdi_device_id
        assert agent.published("worker-0") == ["s1-worker0"]
        spec = agent.published_spec("worker-0", "s1-worker0")
        assert spec.env["TPU_WORKER_ID"] == "0"
        assert spec.env["TPU_TOPOLOGY"] == "2x2x1"
        assert spec.device_nodes == ["/dev/accel0", "/dev/accel1", "/dev/accel2", "/dev/accel3"]

    def test_colocated_groups_get_disjoint_chip_indices(self, world):
        """Two chip groups on ONE host must publish disjoint /dev/accel sets —
        otherwise both containers are handed the same physical chips and each
        group's open fds deadlock the other's drain."""
        store, pool, agent, rec = world
        make_tpu_cr(store, pool, name="a", slice_name="sa", node="worker-0")
        make_tpu_cr(store, pool, name="b", slice_name="sb", node="worker-0")
        for name in ("a", "b"):
            step(rec, name)  # "" -> Attaching
            step(rec, name)  # Attaching -> Online
        spec_a = agent.published_spec("worker-0", "sa-worker0")
        spec_b = agent.published_spec("worker-0", "sb-worker0")
        assert spec_a.device_nodes == ["/dev/accel0", "/dev/accel1",
                                       "/dev/accel2", "/dev/accel3"]
        assert spec_b.device_nodes == ["/dev/accel4", "/dev/accel5",
                                       "/dev/accel6", "/dev/accel7"]
        assert not set(spec_a.device_nodes) & set(spec_b.device_nodes)
        # Persisted for restart stability.
        assert get(store, "a").status.chip_indices == [0, 1, 2, 3]
        assert get(store, "b").status.chip_indices == [4, 5, 6, 7]
        # Releasing group a frees its indices for the next group.
        store.delete(ComposableResource, "a")
        step(rec, "a")  # Online -> Detaching
        step(rec, "a")  # Detaching -> Deleting
        step(rec, "a")  # purge
        make_tpu_cr(store, pool, name="c", slice_name="sc", node="worker-0")
        step(rec, "c")
        step(rec, "c")
        assert get(store, "c").status.chip_indices == [0, 1, 2, 3]

    def test_async_fabric_requeues_without_error(self, world):
        store, _, agent, _ = world
        pool = InMemoryPool(async_steps=2)
        rec = ComposableResourceReconciler(store, pool, FakeNodeAgent(pool=pool))
        make_tpu_cr(store, pool)
        step(rec, "r0")  # -> Attaching
        r = step(rec, "r0")  # fabric: accepted, waiting
        assert r.requeue_after == rec.timing.attach_poll
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_ATTACHING
        assert cr.status.error == ""  # wait sentinel is not an error
        step(rec, "r0")  # still waiting
        step(rec, "r0")  # completes
        assert get(store, "r0").status.state == RESOURCE_STATE_ONLINE

    def test_visibility_delay_polls_then_online(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        agent.set_visibility_delay("worker-0", 2)
        step(rec, "r0")
        r = step(rec, "r0")
        assert r.requeue_after == rec.timing.visibility_poll
        assert get(store, "r0").status.state == RESOURCE_STATE_ATTACHING
        step(rec, "r0")
        step(rec, "r0")
        assert get(store, "r0").status.state == RESOURCE_STATE_ONLINE

    def test_missing_driver_surfaces_error_and_raises(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        agent.set_no_driver("worker-0")
        step(rec, "r0")
        with pytest.raises(Exception):
            step(rec, "r0")
        assert "no libtpu" in get(store, "r0").status.error

    def test_fabric_failure_surfaces_error(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        pool.inject_add_failure("r0")
        step(rec, "r0")
        with pytest.raises(FabricError):
            step(rec, "r0")
        assert "injected attach failure" in get(store, "r0").status.error
        # retry succeeds and clears the error
        step(rec, "r0")
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE and cr.status.error == ""

    def test_gpu_compat_attach(self, world):
        store, pool, agent, rec = world
        make_gpu_cr(store)
        step(rec, "g0")
        step(rec, "g0")
        cr = get(store, "g0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert len(cr.status.device_ids) == 1
        assert agent.published("worker-0") == []  # no CDI for gpu compat


class TestOnlineState:
    def _online(self, world, name="r0"):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool, name=name)
        step(rec, name)
        step(rec, name)
        assert get(store, name).status.state == RESOURCE_STATE_ONLINE
        return store, pool, agent, rec

    def test_healthy_poll_keeps_online(self, world):
        store, pool, agent, rec = self._online(world)
        r = step(rec, "r0")
        assert r.requeue_after == rec.timing.health_poll
        assert get(store, "r0").status.error == ""

    def test_unhealthy_probe_is_damped_then_degrades(self, world):
        """Flap damping (self-healing data plane): a failed probe below the
        threshold writes NOTHING — no status churn, no event spam; at the
        threshold the member transitions to a durable Degraded state with a
        structured failure record."""
        store, pool, agent, rec = self._online(world)
        chip = get(store, "r0").status.device_ids[0]
        pool.set_health(chip, DeviceHealth("Critical", "ICI link down"))
        rv_before = get(store, "r0").metadata.resource_version
        threshold = rec.timing.health_failure_threshold
        for _ in range(threshold - 1):
            step(rec, "r0")
            cr = get(store, "r0")
            # Damped: still Online, no error surfaced, no write at all.
            assert cr.status.state == RESOURCE_STATE_ONLINE
            assert cr.status.error == ""
            assert cr.metadata.resource_version == rv_before
        step(rec, "r0")  # threshold crossed
        cr = get(store, "r0")
        assert cr.status.state == "Degraded"
        assert "Critical" in cr.status.error
        assert cr.status.failure is not None
        assert cr.status.failure.source == "health-probe"
        assert cr.status.failure.probe_failures == threshold
        # Recovery (damped the same way): healthy probes return it Online.
        pool.set_health(chip, DeviceHealth())
        for _ in range(rec.timing.health_recovery_threshold):
            step(rec, "r0")
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert cr.status.error == ""
        assert cr.status.failure is None

    def test_transient_flip_never_writes_status_or_events(self, world):
        """Satellite: a single-probe health flip (bad then good) leaves no
        trace — the store object is untouched and no Unhealthy/Degraded
        event is emitted."""
        store, pool, agent, rec = self._online(world)
        chip = get(store, "r0").status.device_ids[0]
        rv_before = get(store, "r0").metadata.resource_version
        events_before = len(
            rec.recorder.for_object(kind="ComposableResource", name="r0")
        )
        pool.set_health(chip, DeviceHealth("Critical", "transient blip"))
        step(rec, "r0")  # one bad probe
        pool.set_health(chip, DeviceHealth())
        step(rec, "r0")  # flip back — streak resets
        pool.set_health(chip, DeviceHealth("Critical", "another blip"))
        step(rec, "r0")
        pool.set_health(chip, DeviceHealth())
        step(rec, "r0")
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_ONLINE
        assert cr.status.error == ""
        assert cr.metadata.resource_version == rv_before
        assert len(
            rec.recorder.for_object(kind="ComposableResource", name="r0")
        ) == events_before

    def test_delete_moves_to_detaching(self, world):
        store, pool, agent, rec = self._online(world)
        store.delete(ComposableResource, "r0")
        step(rec, "r0")
        assert get(store, "r0").status.state == RESOURCE_STATE_DETACHING


class TestDetachPath:
    def _deleting_online(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")
        step(rec, "r0")
        store.delete(ComposableResource, "r0")
        step(rec, "r0")  # Online -> Detaching
        return store, pool, agent, rec

    def test_full_detach_releases_and_purges(self, world):
        store, pool, agent, rec = self._deleting_online(world)
        step(rec, "r0")  # Detaching: drain+fabric remove+cleanup -> Deleting
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_DELETING
        assert cr.status.device_ids == []
        assert agent.published("worker-0") == []  # CDI retracted
        assert agent.taints() == {}  # quarantine lifted
        step(rec, "r0")  # Deleting -> finalizer removed -> purged
        assert store.try_get(ComposableResource, "r0") is None
        pool.release_slice("s1")
        assert pool.free_chips("tpu-v4") == 64

    def test_busy_device_blocks_detach_until_idle(self, world):
        store, pool, agent, rec = self._deleting_online(world)
        chip = pool.attached_to("worker-0")[0]
        agent.add_load("worker-0", chip)
        r = step(rec, "r0")
        assert r.requeue_after == rec.timing.busy_poll
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_DETACHING
        assert "in use" in cr.status.error
        agent.clear_loads("worker-0")
        step(rec, "r0")
        assert get(store, "r0").status.state == RESOURCE_STATE_DELETING

    def test_force_detach_ignores_loads(self, world):
        store, pool, agent, rec = world
        pool.reserve_slice("s1", "tpu-v4", "2x2x1", ["worker-0"])
        cr = ComposableResource(
            metadata=ObjectMeta(name="r0"),
            spec=ComposableResourceSpec(
                type="tpu", model="tpu-v4", target_node="worker-0", chip_count=4,
                slice_name="s1", worker_id=0, topology="2x2x1", force_detach=True,
            ),
        )
        store.create(cr)
        step(rec, "r0")
        step(rec, "r0")
        chip = pool.attached_to("worker-0")[0]
        agent.add_load("worker-0", chip)
        store.delete(ComposableResource, "r0")
        step(rec, "r0")
        step(rec, "r0")
        assert get(store, "r0").status.state == RESOURCE_STATE_DELETING

    def test_detach_during_attaching_without_devices_goes_straight_to_deleting(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")  # -> Attaching
        store.delete(ComposableResource, "r0")
        step(rec, "r0")
        assert get(store, "r0").status.state == RESOURCE_STATE_DELETING

    def test_taint_created_while_draining_busy(self, world):
        """Quarantine must be in place even while waiting on the fabric."""
        store, _, agent, _ = world
        pool = InMemoryPool(async_steps=2)
        agent = FakeNodeAgent(pool=pool)
        rec = ComposableResourceReconciler(store, pool, agent)
        make_tpu_cr(store, pool)
        step(rec, "r0")
        step(rec, "r0")  # wait
        step(rec, "r0")  # wait
        step(rec, "r0")  # online
        assert get(store, "r0").status.state == RESOURCE_STATE_ONLINE
        store.delete(ComposableResource, "r0")
        step(rec, "r0")  # -> Detaching
        r = step(rec, "r0")  # fabric detach accepted, waiting
        assert r.requeue_after == rec.timing.detach_poll
        assert len(agent.taints()) == 4  # chips quarantined during the wait
        step(rec, "r0")  # still waiting
        step(rec, "r0")  # completes
        assert get(store, "r0").status.state == RESOURCE_STATE_DELETING
        assert agent.taints() == {}


class TestGcAndAdoption:
    def test_node_gone_forces_teardown(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")
        step(rec, "r0")
        store.delete(Node, "worker-0")
        step(rec, "r0")  # GC kicks in
        cr = get(store, "r0")
        assert cr.status.state == RESOURCE_STATE_DELETING
        assert cr.being_deleted
        step(rec, "r0")
        assert store.try_get(ComposableResource, "r0") is None

    def test_ready_to_detach_label_adopted_and_detached(self, world):
        store, pool, agent, rec = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        cr = ComposableResource(
            metadata=ObjectMeta(
                name="detach-cr",
                labels={LABEL_READY_TO_DETACH: leaked},
            ),
            spec=ComposableResourceSpec(type="tpu", model="tpu-v4", target_node="worker-1"),
        )
        store.create(cr)
        step(rec, "detach-cr")  # adopt: device id from label, state=Online
        got = get(store, "detach-cr")
        assert got.status.device_ids == [leaked]
        assert got.status.state == RESOURCE_STATE_ONLINE
        step(rec, "detach-cr")  # Online sees label -> self-delete -> Detaching
        assert get(store, "detach-cr").status.state == RESOURCE_STATE_DETACHING
        before = pool.free_chips("tpu-v4")
        step(rec, "detach-cr")  # detach reclaims the leak
        step(rec, "detach-cr")
        assert store.try_get(ComposableResource, "detach-cr") is None
        assert pool.free_chips("tpu-v4") == before + 1

    def test_reconcile_of_absent_object_is_noop(self, world):
        _, _, _, rec = world
        assert rec.reconcile("ghost").requeue_after == 0


class TestDeletionRaces:
    """Objects vanishing between the reconciler's cache read and its API
    write must mean "already done", never an exception loop — the reference
    wraps every deletion-path call in client.IgnoreNotFound
    (composableresource_controller.go:87,143,160). The stale-copy replays
    below model a watch cache serving a finalizer-bearing copy after the
    server purged (the exact race that crashed BENCH_r03)."""

    @staticmethod
    def _purge(store, name):
        """Concurrent-actor purge: delete + strip finalizers."""
        from tpu_composer.runtime.store import NotFoundError
        try:
            store.delete(ComposableResource, name)
        except NotFoundError:
            return
        obj = store.try_get(ComposableResource, name)
        if obj is not None:
            obj.metadata.finalizers = []
            store.update(obj)
        assert store.try_get(ComposableResource, name) is None

    def test_finalizer_put_races_concurrent_purge(self, world):
        store, pool, agent, rec = world
        make_gpu_cr(store)
        step(rec, "g0")  # finalizer + Attaching
        store.delete(ComposableResource, "g0")
        step(rec, "g0")  # no devices yet -> Deleting
        stale = get(store, "g0")  # the reconciler's stale cache read
        step(rec, "g0")  # a competing pass purges the object for real
        assert store.try_get(ComposableResource, "g0") is None
        r = rec._handle_deleting(stale)  # replay with the stale copy
        assert r.requeue_after == 0

    def test_gc_of_finalizerless_object_purges_cleanly(self, world):
        """delete() on a finalizer-less object purges outright; the GC path
        must not assume a terminating copy survives to re-read."""
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        cr = get(store, "r0")  # never reconciled: no finalizer yet
        cr.status.state = RESOURCE_STATE_ONLINE
        store.update_status(cr)
        store.delete(Node, "worker-0")
        step(rec, "r0")
        assert store.try_get(ComposableResource, "r0") is None

    def test_online_label_teardown_races_purge(self, world):
        store, pool, agent, rec = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        cr = ComposableResource(
            metadata=ObjectMeta(
                name="d0", labels={LABEL_READY_TO_DETACH: leaked}
            ),
            spec=ComposableResourceSpec(
                type="tpu", model="tpu-v4", target_node="worker-1"
            ),
        )
        store.create(cr)
        step(rec, "d0")  # adopt -> Online
        stale = get(store, "d0")
        self._purge(store, "d0")
        r = rec._handle_online(stale)  # self-delete hits 404 -> done
        assert r.requeue_after == 0

    def test_detach_completion_races_purge(self, world):
        store, pool, agent, rec = world
        make_tpu_cr(store, pool)
        step(rec, "r0")
        step(rec, "r0")  # Online
        store.delete(ComposableResource, "r0")
        step(rec, "r0")  # -> Detaching
        stale = get(store, "r0")
        self._purge(store, "r0")
        # The fabric release still runs; the final status PUT 404s quietly.
        r = rec._handle_detaching(stale)
        assert r.requeue_after == rec.timing.detach_fast
        assert pool.attached_to("worker-0") == []
