"""Work queue, controller loop, manager, leader election, metrics."""

import threading
import time
import urllib.request

import pytest

from tpu_composer.api import ComposabilityRequest, ComposabilityRequestSpec, ObjectMeta, ResourceDetails
from tpu_composer.runtime.controller import Controller, Result
from tpu_composer.runtime.leader import LeaderElector
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.metrics import Registry
from tpu_composer.runtime.queue import RateLimitingQueue
from tpu_composer.runtime.store import Store, WatchEvent


def req(name="req-1"):
    return ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(resource=ResourceDetails(type="tpu", model="tpu-v4", size=1)),
    )


class TestQueue:
    def test_dedup_while_queued(self):
        q = RateLimitingQueue()
        q.add("a")
        q.add("a")
        assert q.get(timeout=0.1) == "a"
        assert q.get(timeout=0.05) is None

    def test_readd_while_processing_requeues_on_done(self):
        q = RateLimitingQueue()
        q.add("a")
        key = q.get(timeout=0.1)
        q.add("a")  # in-flight → dirty
        assert q.get(timeout=0.05) is None  # not yet requeued
        q.done(key)
        assert q.get(timeout=0.1) == "a"

    def test_add_after_delays(self):
        q = RateLimitingQueue()
        t0 = time.monotonic()
        q.add_after("a", 0.15)
        assert q.get(timeout=0.05) is None
        assert q.get(timeout=1.0) == "a"
        assert time.monotonic() - t0 >= 0.15

    def test_rate_limited_backoff_grows_and_forget_resets(self):
        q = RateLimitingQueue(base_delay=0.05, max_delay=1.0)
        q.add_rate_limited("a")
        assert q.retries("a") == 1
        q.add_rate_limited("a")
        assert q.retries("a") == 2
        q.forget("a")
        assert q.retries("a") == 0

    def test_forget_invalidates_pending_backoff_entries(self):
        """A key that succeeded (forget) must not be re-woken by a stale
        pre-success failure requeue still sitting in the delay heap."""
        q = RateLimitingQueue(base_delay=0.08, max_delay=1.0)
        q.add_rate_limited("a")  # backoff entry pending
        q.forget("a")  # success before the entry fires
        assert q.get(timeout=0.3) is None  # stale entry evaporated

    def test_forget_then_new_failure_requeues_normally(self):
        q = RateLimitingQueue(base_delay=0.03, max_delay=0.1)
        q.add_rate_limited("a")
        q.forget("a")
        q.add_rate_limited("a")  # NEW failure after the forget
        assert q.get(timeout=1.0) == "a"  # only the fresh entry fires
        q.done("a")
        assert q.get(timeout=0.2) is None

    def test_forget_never_touches_plain_add_after(self):
        """add_after entries are liveness (periodic polls), not backoff —
        a successful reconcile's forget must leave them armed."""
        q = RateLimitingQueue()
        q.add_after("a", 0.1)
        q.forget("a")  # the worker loop forgets on every success
        assert q.get(timeout=1.0) == "a"

    def test_deep_queue_drains_fifo(self):
        q = RateLimitingQueue()
        for i in range(500):
            q.add(i)
        drained = [q.get(timeout=0.1) for _ in range(500)]
        assert drained == list(range(500))

    def test_shutdown_unblocks(self):
        q = RateLimitingQueue()
        out = []

        def getter():
            out.append(q.get())

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.05)
        q.shutdown()
        t.join(timeout=1)
        assert out == [None]


class CountingController(Controller):
    primary_kind = "ComposabilityRequest"

    def __init__(self, store):
        super().__init__(store)
        self.seen = []
        self.reconciled = threading.Event()

    def reconcile(self, name):
        self.seen.append(name)
        self.reconciled.set()
        return Result()


class TestControllerLoop:
    def test_events_drive_reconcile(self, store):
        c = CountingController(store)
        c.start()
        try:
            store.create(req())
            assert c.reconciled.wait(2)
            assert "req-1" in c.seen
        finally:
            c.stop()

    def test_initial_wave_covers_existing_objects(self, store):
        store.create(req("pre-existing"))
        c = CountingController(store)
        c.start()
        try:
            assert c.reconciled.wait(2)
            assert "pre-existing" in c.seen
        finally:
            c.stop()

    def test_secondary_watch_with_mapper_and_predicate(self, store):
        class MappedController(CountingController):
            primary_kind = ""  # only the secondary watch below

        c = MappedController(store)
        c.watch(
            "ComposabilityRequest",
            mapper=lambda ev: [f"mapped-{ev.obj.metadata.name}"],
            predicate=lambda ev: ev.obj.metadata.name != "skip",
        )
        c.start()
        try:
            store.create(req("skip"))
            store.create(req("take"))
            assert c.reconciled.wait(2)
            time.sleep(0.1)
            assert c.seen == ["mapped-take"]
        finally:
            c.stop()

    def test_error_retries_with_backoff(self, store):
        class FlakyController(Controller):
            primary_kind = "ComposabilityRequest"

            def __init__(self, store):
                super().__init__(store)
                self.calls = 0
                self.succeeded = threading.Event()

            def reconcile(self, name):
                self.calls += 1
                if self.calls < 3:
                    raise RuntimeError("boom")
                self.succeeded.set()
                return Result()

        c = FlakyController(store)
        c.start()
        try:
            store.create(req())
            assert c.succeeded.wait(5)
            assert c.calls == 3
        finally:
            c.stop()

    def test_requeue_after_causes_second_reconcile(self, store):
        class RequeueOnce(Controller):
            primary_kind = "ComposabilityRequest"

            def __init__(self, store):
                super().__init__(store)
                self.calls = 0
                self.twice = threading.Event()

            def reconcile(self, name):
                self.calls += 1
                if self.calls >= 2:
                    self.twice.set()
                    return Result()
                return Result(requeue_after=0.05)

        c = RequeueOnce(store)
        c.start()
        try:
            store.create(req())
            assert c.twice.wait(2)
        finally:
            c.stop()


class TestManager:
    def test_health_endpoints_and_metrics(self, store):
        m = Manager(store=store, health_addr="127.0.0.1:0")
        c = CountingController(store)
        m.add_controller(c)
        m.start()
        try:
            port = m.health_port
            body = urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz").read()
            assert body == b"ok"
            ready = urllib.request.urlopen(f"http://127.0.0.1:{port}/readyz")
            assert ready.status == 200
            metrics = urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics").read().decode()
            assert "tpuc_attach_to_ready_seconds" in metrics
        finally:
            m.stop()

    def test_runnable_receives_stop_event(self, store):
        stopped = threading.Event()

        def runnable(stop_event):
            stop_event.wait(5)
            stopped.set()

        m = Manager(store=store)
        m.add_runnable(runnable)
        m.start()
        m.stop()
        assert stopped.wait(1)


class TestLeaderElection:
    def test_second_elector_blocks_until_release(self, tmp_path):
        path = str(tmp_path / "leader.lock")
        a, b = LeaderElector(path), LeaderElector(path)
        assert a.try_acquire()
        assert not b.try_acquire()
        a.release()
        assert b.try_acquire()
        b.release()


class TestMetrics:
    def test_counter_gauge_histogram_exposition(self):
        r = Registry()
        r.counter("c_total", "help").inc(controller="x")
        r.gauge("g", "help").set(3.5, node="n0")
        h = r.histogram("h_seconds", "help", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v, op="attach")
        text = r.expose_text()
        assert 'c_total{controller="x"} 1.0' in text
        assert 'g{node="n0"} 3.5' in text
        assert 'h_seconds_bucket{op="attach",le="+Inf"} 3' in text
        assert h.count(op="attach") == 3
        assert h.percentile(0.5, op="attach") == 0.5

    def test_exposition_round_trip(self):
        """Parse the scrape text back and verify the format invariants a
        real Prometheus scraper depends on: escaped label values
        round-trip, histogram buckets are cumulative and monotonic, the
        explicit +Inf bucket equals _count, and _sum matches the observed
        total — per label set."""
        import re

        def parse(text):
            # sample name -> {frozenset(label items) -> value}
            out = {}
            label_re = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
            for line in text.splitlines():
                if not line or line.startswith("#"):
                    continue
                name_part, value = line.rsplit(" ", 1)
                if "{" in name_part:
                    name, raw = name_part.split("{", 1)
                    labels = {
                        k: v.replace("\\n", "\n").replace('\\"', '"')
                            .replace("\\\\", "\\")
                        for k, v in label_re.findall(raw[:-1])
                    }
                else:
                    name, labels = name_part, {}
                out.setdefault(name, {})[frozenset(labels.items())] = (
                    float(value)
                )
            return out

        r = Registry()
        nasty = 'quo"te\\back\nnewline'
        r.counter("c_total", "help").inc(2, err=nasty)
        r.gauge("g", "help").set(-1.5, node="n0")
        h = r.histogram("h_s", "help", buckets=(0.1, 1.0))
        obs = {"attach": [0.05, 0.05, 0.5, 5.0], "detach": [0.2]}
        for op, values in obs.items():
            for v in values:
                h.observe(v, op=op)
        parsed = parse(r.expose_text())

        assert parsed["c_total"][frozenset([("err", nasty)])] == 2.0
        assert parsed["g"][frozenset([("node", "n0")])] == -1.5
        for op, values in obs.items():
            key = ("op", op)
            buckets = {
                dict(ls)["le"]: v
                for ls, v in parsed["h_s_bucket"].items() if key in ls
            }
            # Explicit +Inf present; cumulative counts monotonic in
            # bucket order and ending at the total observation count.
            assert "+Inf" in buckets
            ordered = [buckets[le] for le in ("0.1", "1.0", "+Inf")]
            assert ordered == sorted(ordered)
            count = parsed["h_s_count"][frozenset([key])]
            assert buckets["+Inf"] == count == len(values)
            total = parsed["h_s_sum"][frozenset([key])]
            assert abs(total - sum(values)) < 1e-9


class TestSecureMetrics:
    """Dedicated TLS + bearer-token metrics endpoint (VERDICT r2 weak #7;
    reference cmd/main.go:109-127 serves HTTPS metrics behind an authn/authz
    filter — this is the standalone analog)."""

    @pytest.fixture()
    def tls(self, tmp_path):
        import subprocess

        cert, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=127.0.0.1"],
            check=True, capture_output=True,
        )
        return str(cert), str(key)

    def test_token_and_tls_enforced(self, tls, tmp_path):
        import ssl
        import urllib.error
        import urllib.request

        from tpu_composer.runtime.manager import Manager

        cert, key = tls
        token = tmp_path / "token"
        token.write_text("scrape-secret\n")
        mgr = Manager(
            health_addr="127.0.0.1:0",
            metrics_addr="127.0.0.1:0",
            metrics_certfile=cert,
            metrics_keyfile=key,
            metrics_token_file=str(token),
        )
        mgr.start()
        try:
            ctx = ssl.create_default_context(cafile=cert)
            ctx.check_hostname = False
            base = f"https://127.0.0.1:{mgr.metrics_port}/metrics"

            # No token -> 401.
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base, context=ctx)
            assert exc.value.code == 401

            # Correct bearer token -> Prometheus text.
            req = urllib.request.Request(
                base, headers={"Authorization": "Bearer scrape-secret"}
            )
            body = urllib.request.urlopen(req, context=ctx).read().decode()
            assert "tpuc" in body or "# " in body

            # Token rotation without restart: file is re-read per request.
            token.write_text("rotated\n")
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req, context=ctx)
            req2 = urllib.request.Request(
                base, headers={"Authorization": "Bearer rotated"}
            )
            assert urllib.request.urlopen(req2, context=ctx).status == 200

            # The plain health port no longer leaks metrics.
            health = f"http://127.0.0.1:{mgr.health_port}/metrics"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(health)
            assert exc.value.code == 404
        finally:
            mgr.stop()
