"""Cluster scheduler unit + edge cases: placement scoring, gang admission,
backfill gate, preemption victim-set minimality, priority inversion with
quarantined nodes, defrag planning.

Driven exactly like test_request_controller.py — reconcilers stepped by
hand, one transition at a time — plus direct engine/preemptor/planner calls
where the decision itself (not the execution) is under test."""

from __future__ import annotations

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.publisher import DevicePublisher
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.crdgen import COMPOSABILITY_REQUEST_SCHEMA
from tpu_composer.api.types import (
    LABEL_MANAGED_BY,
    PREEMPT_NEVER,
    REQUEST_STATE_RUNNING,
    REQUEST_STATE_UPDATING,
    ValidationError,
)
from tpu_composer.controllers.request_controller import (
    AllocationError,
    ComposabilityRequestReconciler,
)
from tpu_composer.controllers.resource_controller import ComposableResourceReconciler
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import FabricError
from tpu_composer.runtime.store import Store
from tpu_composer.scheduler import PlacementEngine, host_index
from tpu_composer.topology.slices import TopologyError, solve_slice


def make_world(n_nodes=4, slots=4, chips=None):
    store = Store()
    for i in range(n_nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = slots
        n.status.milli_cpu = 8000
        n.status.memory = 64 << 30
        n.status.allowed_pod_number = 100
        store.create(n)
    pool = InMemoryPool(chips=chips or {"tpu-v4": 64})
    agent = FakeNodeAgent(pool=pool)
    req_rec = ComposabilityRequestReconciler(store, pool)
    res_rec = ComposableResourceReconciler(
        store, pool, agent,
        decision_ledger=req_rec.scheduler.ledger,
    )
    return store, pool, req_rec, res_rec


def make_request(store, name, size=4, priority=0, policy="", target=""):
    spec = ComposabilityRequestSpec(
        resource=ResourceDetails(
            type="tpu", model="tpu-v4", size=size, target_node=target
        ),
        priority=priority,
    )
    if policy:
        spec.preemption_policy = policy
    return store.create(
        ComposabilityRequest(metadata=ObjectMeta(name=name), spec=spec)
    )


def pump(store, req_rec, res_rec, steps=40):
    """Step every request + resource reconciler, tolerating the expected
    operational errors (AllocationError and friends land in status)."""
    for _ in range(steps):
        for r in store.list(ComposabilityRequest):
            try:
                req_rec.reconcile(r.metadata.name)
            except (FabricError, TopologyError):
                pass
        for c in store.list(ComposableResource):
            try:
                res_rec.reconcile(c.metadata.name)
            except FabricError:
                pass


def run_to_ready(store, req_rec, res_rec, name, max_steps=60):
    for _ in range(max_steps):
        pump(store, req_rec, res_rec, steps=1)
        if store.get(ComposabilityRequest, name).status.state == REQUEST_STATE_RUNNING:
            return
    raise AssertionError(
        f"{name} never reached Running:"
        f" {store.get(ComposabilityRequest, name).status.to_dict()}"
    )


# ---------------------------------------------------------------------------
# spec fields + schema
# ---------------------------------------------------------------------------
class TestSpecFields:
    def test_priority_and_policy_roundtrip(self):
        spec = ComposabilityRequestSpec(
            resource=ResourceDetails(model="tpu-v4", size=4),
            priority=100,
            preemption_policy=PREEMPT_NEVER,
        )
        spec.validate()
        again = ComposabilityRequestSpec.from_dict(spec.to_dict())
        assert again.priority == 100
        assert again.preemption_policy == PREEMPT_NEVER

    def test_defaults_not_serialized(self):
        d = ComposabilityRequestSpec(
            resource=ResourceDetails(model="tpu-v4", size=4)
        ).to_dict()
        assert "priority" not in d and "preemptionPolicy" not in d

    def test_invalid_policy_rejected(self):
        spec = ComposabilityRequestSpec(
            resource=ResourceDetails(model="tpu-v4", size=4),
            preemption_policy="Sometimes",
        )
        with pytest.raises(ValidationError):
            spec.validate()

    def test_priority_bounds(self):
        spec = ComposabilityRequestSpec(
            resource=ResourceDetails(model="tpu-v4", size=4),
            priority=2_000_000_000,
        )
        with pytest.raises(ValidationError):
            spec.validate()

    def test_crd_schema_carries_scheduler_fields(self):
        props = COMPOSABILITY_REQUEST_SCHEMA["properties"]["spec"]["properties"]
        assert props["priority"]["type"] == "integer"
        assert "Never" in props["preemptionPolicy"]["enum"]


# ---------------------------------------------------------------------------
# placement engine
# ---------------------------------------------------------------------------
class TestPlacementEngine:
    def test_host_index(self):
        assert host_index("worker-12") == 12
        assert host_index("tpu-host-3") == 3
        assert host_index("gateway") is None

    def test_tightest_fit_packs_fragmented_host(self):
        store, pool, req_rec, res_rec = make_world()
        make_request(store, "frag", size=2, target="worker-2")
        run_to_ready(store, req_rec, res_rec, "frag")
        # A 2-chip group should land in worker-2's gap, not a fresh host.
        make_request(store, "r2", size=2)
        run_to_ready(store, req_rec, res_rec, "r2")
        req = store.get(ComposabilityRequest, "r2")
        assert req.status.slice.worker_hostnames == ["worker-2"]

    def test_contiguity_tiebreak_prefers_adjacent_window(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        # Occupy worker-1 fully: the remaining free hosts are 0, 2, 3.
        make_request(store, "hole", size=4, target="worker-1")
        run_to_ready(store, req_rec, res_rec, "hole")
        # A 2-host slice must prefer the contiguous (2,3) window over the
        # lexicographic-first but gapped (0,2) pair.
        make_request(store, "pair", size=8)
        run_to_ready(store, req_rec, res_rec, "pair")
        req = store.get(ComposabilityRequest, "pair")
        assert req.status.slice.worker_hostnames == ["worker-2", "worker-3"]

    def test_fragmentation_score(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        engine = PlacementEngine(store)
        assert engine.fragmentation(set()) == 0.0  # all capacity whole
        make_request(store, "r1", size=2, target="worker-0")
        run_to_ready(store, req_rec, res_rec, "r1")
        # free: 2 on worker-0 (stranded) + 12 whole -> 1 - 12/14
        assert engine.fragmentation(set()) == pytest.approx(1 - 12 / 14)

    def test_full_cluster_is_not_fragmented(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "r1", size=4)
        run_to_ready(store, req_rec, res_rec, "r1")
        assert PlacementEngine(store).fragmentation(set()) == 0.0


# ---------------------------------------------------------------------------
# gang admission
# ---------------------------------------------------------------------------
class TestGangAdmission:
    def test_exactly_full_capacity_admits_one_gang_whole(self):
        """Two 2-host gangs race into a 2-host cluster: one composes fully,
        the other holds NOTHING (no half-allocated deadlock), and recovers
        the moment the winner leaves."""
        store, pool, req_rec, res_rec = make_world(n_nodes=2)
        make_request(store, "gang-a", size=8)
        make_request(store, "gang-b", size=8)
        pump(store, req_rec, res_rec)
        states = {
            n: store.get(ComposabilityRequest, n).status.state
            for n in ("gang-a", "gang-b")
        }
        assert sorted(states.values()) == ["", REQUEST_STATE_RUNNING]
        winner = next(n for n, s in states.items() if s == REQUEST_STATE_RUNNING)
        loser = next(n for n, s in states.items() if s != REQUEST_STATE_RUNNING)
        # The loser owns zero children and zero placeholder claims.
        assert not store.list(
            ComposableResource, label_selector={LABEL_MANAGED_BY: loser}
        )
        assert store.get(ComposabilityRequest, loser).status.error
        store.delete(ComposabilityRequest, winner)
        pump(store, req_rec, res_rec)
        run_to_ready(store, req_rec, res_rec, loser)

    def test_equal_priority_no_preemption(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "first", size=4)
        run_to_ready(store, req_rec, res_rec, "first")
        make_request(store, "second", size=4, priority=0)
        pump(store, req_rec, res_rec, steps=5)
        # Equal priority never evicts.
        assert store.get(ComposabilityRequest, "first").status.state == REQUEST_STATE_RUNNING
        assert store.get(ComposabilityRequest, "second").status.error


# ---------------------------------------------------------------------------
# preemption
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_victim_set_is_minimal(self):
        """One 4-chip victim beats two 2-chip victims for a whole-host
        demand: minimality is cardinality-first."""
        store, pool, req_rec, res_rec = make_world(n_nodes=2)
        make_request(store, "small-a", size=2, target="worker-0")
        make_request(store, "small-b", size=2, target="worker-0")
        make_request(store, "big-c", size=4, target="worker-1")
        for n in ("small-a", "small-b", "big-c"):
            run_to_ready(store, req_rec, res_rec, n)
        hp = make_request(store, "hp", size=4, priority=100)
        engine = req_rec.scheduler.engine
        victims = req_rec.scheduler.preemptor.compute_victims(
            hp, solve_slice("tpu-v4", 4), set(),
            engine.used_slots_map("hp"),
        )
        assert victims == ["big-c"]

    def test_fewest_chips_among_equal_cardinality(self):
        """Both a 2-chip and a 4-chip eviction would free a host: take the
        cheaper one."""
        store, pool, req_rec, res_rec = make_world(n_nodes=2)
        make_request(store, "cheap", size=2, target="worker-0")
        make_request(store, "pricey", size=4, target="worker-1")
        for n in ("cheap", "pricey"):
            run_to_ready(store, req_rec, res_rec, n)
        hp = make_request(store, "hp", size=4, priority=100)
        victims = req_rec.scheduler.preemptor.compute_victims(
            hp, solve_slice("tpu-v4", 4), set(),
            req_rec.scheduler.engine.used_slots_map("hp"),
        )
        assert victims == ["cheap"]

    def test_never_policy_protects_victim(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "protected", size=4, policy=PREEMPT_NEVER)
        run_to_ready(store, req_rec, res_rec, "protected")
        make_request(store, "hp", size=4, priority=100)
        pump(store, req_rec, res_rec, steps=5)
        assert (
            store.get(ComposabilityRequest, "protected").status.state
            == REQUEST_STATE_RUNNING
        )
        assert store.get(ComposabilityRequest, "hp").status.error

    def test_never_policy_preemptor_does_not_evict(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "batch", size=4)
        run_to_ready(store, req_rec, res_rec, "batch")
        make_request(store, "hp", size=4, priority=100, policy=PREEMPT_NEVER)
        pump(store, req_rec, res_rec, steps=5)
        assert (
            store.get(ComposabilityRequest, "batch").status.state
            == REQUEST_STATE_RUNNING
        )

    def test_preempt_clears_placeholder_rows_of_allocating_victim(self):
        """A victim caught mid-re-solve (already NodeAllocating, e.g. after
        a Degraded event) still holds placeholder capacity claims in
        status.resources — preemption must clear them, or used_slots_map
        keeps counting them once its children purge and the preemptor
        names the same victim every pass."""
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "victim", size=2)
        run_to_ready(store, req_rec, res_rec, "victim")
        v = store.get(ComposabilityRequest, "victim")
        v.status.state = "NodeAllocating"  # mid-re-solve snapshot
        store.update_status(v)
        assert v.status.resources  # rows present before eviction
        hp = make_request(store, "hp", size=4, priority=100)
        req_rec._preempt(hp, ["victim"])
        v = store.get(ComposabilityRequest, "victim")
        assert v.status.resources == {}
        assert "preempted" in v.status.error

    def test_victims_on_quarantined_nodes_not_chosen(self):
        """Evicting a workload whose capacity the engine can't use anyway
        is pure disruption — the quarantine-aware inversion guard."""
        store, pool, req_rec, res_rec = make_world(n_nodes=2)
        make_request(store, "doomed", size=4, target="worker-0")
        make_request(store, "alive", size=4, target="worker-1")
        for n in ("doomed", "alive"):
            run_to_ready(store, req_rec, res_rec, n)
        DevicePublisher(store).quarantine_node("worker-0", "test")
        hp = make_request(store, "hp", size=4, priority=100)
        victims = req_rec.scheduler.preemptor.compute_victims(
            hp, solve_slice("tpu-v4", 4), {"worker-0"},
            req_rec.scheduler.engine.used_slots_map("hp"),
        )
        assert victims == ["alive"]


# ---------------------------------------------------------------------------
# backfill gate / priority inversion
# ---------------------------------------------------------------------------
class TestBackfillGate:
    def test_low_priority_held_back_for_feasible_high_priority(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=1)
        make_request(store, "occupant", size=4, policy=PREEMPT_NEVER)
        run_to_ready(store, req_rec, res_rec, "occupant")
        make_request(store, "hp", size=4, priority=50)
        pump(store, req_rec, res_rec, steps=3)  # hp queues (Never blocks eviction)
        store.delete(ComposabilityRequest, "occupant")
        # Drain ONLY the occupant — hp must not get a retry yet, so the
        # window where capacity is back but the queue still holds hp is
        # exactly what the new lp request races into.
        for _ in range(20):
            try:
                req_rec.reconcile("occupant")
            except FabricError:
                pass
            for c in store.list(ComposableResource):
                try:
                    res_rec.reconcile(c.metadata.name)
                except FabricError:
                    pass
            if not store.list(ComposableResource) and store.try_get(
                ComposabilityRequest, "occupant"
            ) is None:
                break
        make_request(store, "lp", size=4, priority=0)
        with pytest.raises(AllocationError, match="held back"):
            req_rec.reconcile("lp")
        run_to_ready(store, req_rec, res_rec, "hp")
        assert store.get(ComposabilityRequest, "lp").status.state != REQUEST_STATE_RUNNING

    def test_scalar_request_cannot_backfill_steal_from_pending_slice(self):
        """gpu devices consume the same host ports as slice workers, so a
        priority-0 scalar placement must respect the gate protecting a
        feasible higher-priority pending slice."""
        store, pool, req_rec, res_rec = make_world(
            n_nodes=1, chips={"tpu-v4": 64, "gpu-a100": 8}
        )
        make_request(store, "occupant", size=4, policy=PREEMPT_NEVER)
        run_to_ready(store, req_rec, res_rec, "occupant")
        make_request(store, "hp", size=4, priority=50)
        pump(store, req_rec, res_rec, steps=3)  # hp queues
        store.delete(ComposabilityRequest, "occupant")
        for _ in range(20):
            try:
                req_rec.reconcile("occupant")
            except FabricError:
                pass
            for c in store.list(ComposableResource):
                try:
                    res_rec.reconcile(c.metadata.name)
                except FabricError:
                    pass
            if not store.list(ComposableResource) and store.try_get(
                ComposabilityRequest, "occupant"
            ) is None:
                break
        store.create(
            ComposabilityRequest(
                metadata=ObjectMeta(name="gpu"),
                spec=ComposabilityRequestSpec(
                    resource=ResourceDetails(
                        type="gpu", model="gpu-a100", size=1
                    )
                ),
            )
        )
        with pytest.raises(AllocationError, match="held back"):
            req_rec.reconcile("gpu")
        run_to_ready(store, req_rec, res_rec, "hp")

    def test_grow_onto_contended_host_cannot_slip_the_gate(self):
        """The gate must probe with the placer's OWN holdings included: a
        samenode gpu request holding 2 ports that grows by 1 must not read
        its own 2 ports as free and starve a feasible pending
        higher-priority demand for the remaining capacity."""
        store, pool, req_rec, res_rec = make_world(
            n_nodes=1, chips={"tpu-v4": 64, "gpu-a100": 8}
        )
        store.create(
            ComposabilityRequest(
                metadata=ObjectMeta(name="gpu"),
                spec=ComposabilityRequestSpec(
                    resource=ResourceDetails(
                        type="gpu", model="gpu-a100", size=2
                    )
                ),
            )
        )
        run_to_ready(store, req_rec, res_rec, "gpu")  # holds 2 of 4 ports
        make_request(store, "hp", size=2, priority=100)  # needs 2 ports
        # hp is feasible RIGHT NOW but pending (simulate the pre-retry
        # window by registering it without letting it place).
        from tpu_composer.topology.slices import solve_slice as _solve
        shape = _solve("tpu-v4", 2)
        req_rec.scheduler.queue.note_pending(
            store.get(ComposabilityRequest, "hp"),
            shape.num_hosts, shape.chips_per_host,
        )
        gpu = store.get(ComposabilityRequest, "gpu")
        gpu.spec.resource.size = 3
        store.update(gpu)
        req_rec.reconcile("gpu")  # Running -> NodeAllocating (spec drift)
        with pytest.raises(AllocationError, match="held back"):
            req_rec.reconcile("gpu")  # the actual grow placement
        run_to_ready(store, req_rec, res_rec, "hp")

    def test_anchored_pending_demand_counts_only_the_delta(self):
        """A partially-placed samenode request's pending demand is the
        DELTA on its anchor: probing delta+held against the full occupancy
        map double-counts and reads a perfectly satisfiable request as
        'unsatisfiable either way', silently dropping its protection."""
        store, pool, req_rec, res_rec = make_world(
            n_nodes=1, slots=8, chips={"tpu-v4": 64, "gpu-a100": 16}
        )

        def mk_gpu(name, size, priority=0):
            store.create(
                ComposabilityRequest(
                    metadata=ObjectMeta(name=name),
                    spec=ComposabilityRequestSpec(
                        resource=ResourceDetails(
                            type="gpu", model="gpu-a100", size=size
                        ),
                        priority=priority,
                    ),
                )
            )

        mk_gpu("hi", 4, priority=100)
        run_to_ready(store, req_rec, res_rec, "hi")  # holds 4 of 8 ports
        mk_gpu("peer", 3)  # scalar peer: no preemption path to evict it
        run_to_ready(store, req_rec, res_rec, "peer")  # 7 used, 1 free
        hi = store.get(ComposabilityRequest, "hi")
        hi.spec.resource.size = 6  # wants 2 more; only 1 free -> queues
        store.update(hi)
        pump(store, req_rec, res_rec, steps=3)
        assert store.get(ComposabilityRequest, "hi").status.error
        store.delete(ComposabilityRequest, "peer")
        for _ in range(30):
            try:
                req_rec.reconcile("peer")
            except FabricError:
                pass
            for c in store.list(ComposableResource):
                try:
                    res_rec.reconcile(c.metadata.name)
                except FabricError:
                    pass
            if store.try_get(ComposabilityRequest, "peer") is None and all(
                c.spec.target_node != "worker-0"
                or c.metadata.labels.get(LABEL_MANAGED_BY) == "hi"
                for c in store.list(ComposableResource)
            ):
                break
        # 4 free; hi's delta (2 on its anchor) is feasible RIGHT NOW. A
        # priority-0 request for 3 ports must be held back, not granted.
        mk_gpu("lo", 3, priority=0)
        with pytest.raises(AllocationError, match="held back"):
            req_rec.reconcile("lo")
        run_to_ready(store, req_rec, res_rec, "hi")
        assert len([
            c for c in store.list(ComposableResource)
            if c.metadata.labels.get(LABEL_MANAGED_BY) == "hi"
        ]) == 6

    def test_unsatisfiable_high_priority_does_not_starve_cluster(self):
        """Priority inversion with quarantine: a pending priority-100
        request whose only candidate host is quarantined must not hold
        back lower-priority work elsewhere."""
        store, pool, req_rec, res_rec = make_world(n_nodes=2)
        DevicePublisher(store).quarantine_node("worker-0", "fabric dead")
        make_request(store, "hp", size=4, priority=100, target="worker-0")
        pump(store, req_rec, res_rec, steps=3)
        assert store.get(ComposabilityRequest, "hp").status.error
        make_request(store, "lp", size=4, priority=0)
        run_to_ready(store, req_rec, res_rec, "lp")
        req = store.get(ComposabilityRequest, "lp")
        assert req.status.slice.worker_hostnames == ["worker-1"]


# ---------------------------------------------------------------------------
# defragmentation planner
# ---------------------------------------------------------------------------
class TestDefrag:
    def _fragmented_world(self):
        """Two hosts each half-full (one 2-chip survivor apiece), two empty:
        defrag should consolidate the survivors onto one host."""
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        for i, name in enumerate(["r1", "r2", "r3", "r4"]):
            make_request(store, name, size=2)
            run_to_ready(store, req_rec, res_rec, name)
        # r1+r2 packed worker-0, r3+r4 packed worker-1; punch holes:
        store.delete(ComposabilityRequest, "r2")
        store.delete(ComposabilityRequest, "r4")
        pump(store, req_rec, res_rec)
        return store, pool, req_rec, res_rec

    def test_plan_is_pure_and_deterministic(self):
        store, pool, req_rec, res_rec = self._fragmented_world()
        planner = req_rec.scheduler.defrag
        p1 = planner.plan()
        p2 = planner.plan()
        assert p1.migrations and p1.migrations == p2.migrations
        assert p1.frag_after < p1.frag_before
        # Dry run: nothing moved.
        assert all(
            not c.being_deleted for c in store.list(ComposableResource)
        )

    def test_execute_consolidates_and_is_idempotent(self):
        store, pool, req_rec, res_rec = self._fragmented_world()
        planner = req_rec.scheduler.defrag
        plan = planner.plan()
        assert len(plan.migrations) == 1
        started = planner.execute(plan)
        assert started == 1
        pump(store, req_rec, res_rec)
        # Both survivors ended on one host; every request still Running.
        for name in ("r1", "r3"):
            assert (
                store.get(ComposabilityRequest, name).status.state
                == REQUEST_STATE_RUNNING
            )
        hosts = {
            c.spec.target_node
            for c in store.list(ComposableResource)
            if not c.being_deleted
        }
        assert len(hosts) == 1
        # Idempotent: a settled cluster yields an empty plan.
        assert planner.plan().empty

    def test_never_policy_pins_worker(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        for name, policy in (("r1", PREEMPT_NEVER), ("r3", "")):
            make_request(store, name, size=2, policy=policy)
            run_to_ready(store, req_rec, res_rec, name)
        # Both packed onto worker-0 — nothing to defrag anyway, but build
        # the scattered case explicitly via a second host:
        make_request(store, "r5", size=4, target="worker-1")
        run_to_ready(store, req_rec, res_rec, "r5")
        store.delete(ComposabilityRequest, "r3")
        pump(store, req_rec, res_rec)
        # worker-0 now holds only the Never-policy r1: it must not migrate.
        assert req_rec.scheduler.defrag.plan().empty

    def test_no_churn_migration_off_a_freshly_packed_target(self):
        """A host that an earlier migration packed chips onto must not be
        'vacated' of only its original occupants — that would disrupt a
        worker without freeing the host. Layout: movable survivors on
        worker-0/1, a pinned (Never) survivor on worker-2; the only sound
        plan is ONE migration 0->1."""
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        order = [("r1", ""), ("r2", ""), ("r3", ""), ("r4", ""),
                 ("r5", PREEMPT_NEVER), ("r6", "")]
        for name, policy in order:
            make_request(store, name, size=2, policy=policy)
            run_to_ready(store, req_rec, res_rec, name)
        for name in ("r2", "r4", "r6"):  # punch holes on all three hosts
            store.delete(ComposabilityRequest, name)
        pump(store, req_rec, res_rec)
        plan = req_rec.scheduler.defrag.plan()
        assert len(plan.migrations) == 1
        (m,) = plan.migrations
        assert (m.from_node, m.to_node) == ("worker-0", "worker-1")

    def test_multi_host_members_never_migrate(self):
        store, pool, req_rec, res_rec = make_world(n_nodes=4)
        make_request(store, "gang", size=8)  # 2 hosts, whole-host members
        run_to_ready(store, req_rec, res_rec, "gang")
        make_request(store, "single", size=2)
        run_to_ready(store, req_rec, res_rec, "single")
        plan = req_rec.scheduler.defrag.plan()
        assert all(m.request != "gang" for m in plan.migrations)
