"""Deterministic cluster-simulation suite for the scheduler.

Replays arrival/departure traces against the full operator stack (request
reconciler + resource reconciler + scheduler + in-memory fabric), stepping
reconciles by hand so every run is deterministic. The acceptance scenario —
a priority-100 2-host gang preempting exactly the minimal priority-0 victim
on a fragmented cluster, then the victim recovering once capacity returns —
runs in tier-1; the long seeded trace replays are marked ``sim`` (and
``slow``, so tier-1's `-m 'not slow'` excludes them; run with `-m sim`).
"""

from __future__ import annotations

import random

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import (
    LABEL_MANAGED_BY,
    REQUEST_STATE_RUNNING,
)
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
)
from tpu_composer.controllers.resource_controller import ComposableResourceReconciler
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import FabricError
from tpu_composer.runtime.store import Store
from tpu_composer.topology.slices import TopologyError


class Cluster:
    """The simulation harness: a store + reconcilers + step/pump helpers."""

    def __init__(self, n_nodes=4, slots=4, chips=256):
        self.store = Store()
        for i in range(n_nodes):
            n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
            n.status.tpu_slots = slots
            n.status.milli_cpu = 8000
            n.status.memory = 64 << 30
            n.status.allowed_pod_number = 100
            self.store.create(n)
        self.slots = slots
        self.pool = InMemoryPool(chips={"tpu-v4": chips})
        agent = FakeNodeAgent(pool=self.pool)
        self.req_rec = ComposabilityRequestReconciler(self.store, self.pool)
        self.res_rec = ComposableResourceReconciler(
            self.store, self.pool, agent,
            decision_ledger=self.req_rec.scheduler.ledger,
        )

    # -- trace events --------------------------------------------------
    def arrive(self, name, size, priority=0, target=""):
        self.store.create(
            ComposabilityRequest(
                metadata=ObjectMeta(name=name),
                spec=ComposabilityRequestSpec(
                    resource=ResourceDetails(
                        type="tpu", model="tpu-v4", size=size,
                        target_node=target,
                    ),
                    priority=priority,
                ),
            )
        )

    def depart(self, name):
        if self.store.try_get(ComposabilityRequest, name) is not None:
            self.store.delete(ComposabilityRequest, name)

    def step(self):
        for r in self.store.list(ComposabilityRequest):
            try:
                self.req_rec.reconcile(r.metadata.name)
            except (FabricError, TopologyError):
                pass
        for c in self.store.list(ComposableResource):
            try:
                self.res_rec.reconcile(c.metadata.name)
            except FabricError:
                pass

    def pump(self, steps=30):
        for _ in range(steps):
            self.step()

    # -- observers -----------------------------------------------------
    def req(self, name):
        return self.store.get(ComposabilityRequest, name)

    def state(self, name):
        r = self.store.try_get(ComposabilityRequest, name)
        return r.status.state if r is not None else "<gone>"

    def children(self, name):
        return self.store.list(
            ComposableResource, label_selector={LABEL_MANAGED_BY: name}
        )

    def live_used(self):
        used = {}
        for c in self.store.list(ComposableResource):
            if not c.being_deleted:
                used[c.spec.target_node] = (
                    used.get(c.spec.target_node, 0) + c.spec.chip_count
                )
        return used

    def check_invariants(self):
        """Safety properties that must hold at EVERY step of a replay."""
        # 1. No host oversubscription by live (non-terminating) children.
        for node, used in self.live_used().items():
            assert used <= self.slots, f"{node} oversubscribed: {used}"
        # 2. Gang atomicity: a Running multi-host slice has every member.
        for r in self.store.list(ComposabilityRequest):
            if (
                r.status.state == REQUEST_STATE_RUNNING
                and r.spec.resource.size > 0
                and r.status.slice.num_hosts
            ):
                live = [c for c in self.children(r.name) if not c.being_deleted]
                assert len(live) == r.status.slice.num_hosts, (
                    f"{r.name}: {len(live)}/{r.status.slice.num_hosts} members"
                )
                assert (
                    len({c.spec.target_node for c in live})
                    == r.status.slice.num_hosts
                )
        # 3. Every decision explains itself: the decision ledger has a
        #    record for every executed placement whose chosen hosts match
        #    what actually ran, and every request stuck in allocation
        #    carries a hold-back/preempt record saying why.
        led = self.req_rec.scheduler.ledger
        assert led is not None
        for r in self.store.list(ComposabilityRequest):
            if (
                r.status.state == REQUEST_STATE_RUNNING
                and r.spec.resource.size > 0
                and r.status.slice.num_hosts
            ):
                rec = led.latest_placed(r.name)
                assert rec is not None, f"{r.name} placed without a record"
                if rec.kind == "place":
                    assert sorted(rec.chosen) == sorted(
                        r.status.slice.worker_hostnames
                    ), (
                        f"{r.name}: record chose {rec.chosen}, execution"
                        f" ran on {r.status.slice.worker_hostnames}"
                    )
                else:  # place-extra: a grow/repair delta within the slice
                    assert set(rec.chosen) <= set(
                        r.status.slice.worker_hostnames
                    ), r.name
            elif r.status.state in ("", "NodeAllocating") and r.status.error:
                assert led.latest(r.name) is not None, (
                    f"{r.name} queued ({r.status.error!r}) with no"
                    " decision record"
                )


# ---------------------------------------------------------------------------
# Acceptance scenario (ISSUE 2): preempt-minimal, recover-on-capacity.
# ---------------------------------------------------------------------------
class TestPreemptionEndToEnd:
    def test_priority_100_gang_preempts_minimal_victims_and_victim_recovers(self):
        sim = Cluster(n_nodes=4, slots=4)
        # Fragment the cluster: two hosts FULL with whole-host batch jobs,
        # one host half-full, one free. A 2-host gang cannot fit although
        # 6 free chips exist.
        sim.arrive("batch-w2", size=4, target="worker-2")
        sim.arrive("batch-w3", size=4, target="worker-3")
        sim.arrive("frag-w1", size=2, target="worker-1")
        sim.pump()
        for n in ("batch-w2", "batch-w3", "frag-w1"):
            assert sim.state(n) == REQUEST_STATE_RUNNING, n

        # Priority-100 2-host slice (2x2x2 = 8 chips over 2 hosts).
        sim.arrive("inference", size=8, priority=100)
        sim.pump(60)
        sim.check_invariants()

        # The gang composed on the freed pair...
        assert sim.state("inference") == REQUEST_STATE_RUNNING
        inf = sim.req("inference")
        assert sorted(inf.status.slice.worker_hostnames) == [
            "worker-0", "worker-1",
        ]
        # ...by evicting EXACTLY the minimal victim set: the 2-chip
        # fragment (cheapest single eviction), never the whole-host jobs.
        assert sim.state("batch-w2") == REQUEST_STATE_RUNNING
        assert sim.state("batch-w3") == REQUEST_STATE_RUNNING
        victim = sim.req("frag-w1")
        assert victim.status.state != REQUEST_STATE_RUNNING
        assert "preempted" in victim.status.error or victim.status.error
        assert not [
            c for c in sim.children("frag-w1") if not c.being_deleted
        ]

        # Victim re-queues and recovers once the gang departs.
        sim.depart("inference")
        sim.pump(60)
        assert sim.state("frag-w1") == REQUEST_STATE_RUNNING
        sim.check_invariants()

    def test_preemption_event_trail(self):
        """The operator can see who evicted whom: Preempted on the victim,
        Preempting on the aggressor."""
        sim = Cluster(n_nodes=1, slots=4)
        sim.arrive("batch", size=4)
        sim.pump()
        sim.arrive("urgent", size=4, priority=10)
        sim.pump(60)
        assert sim.state("urgent") == REQUEST_STATE_RUNNING
        reasons = {e.reason for e in sim.req_rec.recorder.all()}
        assert {"Preempted", "Preempting"} <= reasons


# ---------------------------------------------------------------------------
# Seeded trace replays
# ---------------------------------------------------------------------------
def _replay(sim: Cluster, rng: random.Random, n_events: int,
            check_every: int = 1) -> None:
    """Random arrivals/departures with invariant checks between events."""
    sizes = [1, 2, 4, 8]
    priorities = [0, 0, 0, 50, 100]
    live: list = []
    counter = 0
    for ev in range(n_events):
        if live and rng.random() < 0.4:
            sim.depart(live.pop(rng.randrange(len(live))))
        else:
            counter += 1
            name = f"req-{counter}"
            sim.arrive(name, size=rng.choice(sizes),
                       priority=rng.choice(priorities))
            live.append(name)
        sim.pump(steps=rng.randint(2, 8))
        if ev % check_every == 0:
            sim.check_invariants()
    # Drain everything: the cluster must come back fully free.
    for name in live:
        sim.depart(name)
    sim.pump(60)
    sim.check_invariants()
    assert sim.live_used() == {}
    assert sim.pool.free_chips("tpu-v4") == sim.pool._chips["tpu-v4"]


class TestTraceReplaySmoke:
    def test_short_replay_tier1(self):
        sim = Cluster(n_nodes=4, slots=4)
        _replay(sim, random.Random(7), n_events=25)


@pytest.mark.sim
@pytest.mark.slow
class TestTraceReplayLong:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_long_replay(self, seed):
        sim = Cluster(n_nodes=8, slots=4, chips=512)
        _replay(sim, random.Random(seed), n_events=120, check_every=4)

    def test_priority_storm_converges(self):
        """Burst of mixed-priority gangs onto a small cluster: every
        surviving top-priority request must end Running, and nothing
        oversubscribes while the storm churns."""
        sim = Cluster(n_nodes=4, slots=4)
        rng = random.Random(42)
        for i in range(12):
            sim.arrive(f"storm-{i}", size=rng.choice([2, 4, 8]),
                       priority=rng.choice([0, 100]))
        sim.pump(120)
        sim.check_invariants()
        running_prio = [
            r.spec.priority
            for r in sim.store.list(ComposabilityRequest)
            if r.status.state == REQUEST_STATE_RUNNING
        ]
        pending_prio = [
            r.spec.priority
            for r in sim.store.list(ComposabilityRequest)
            if r.status.state != REQUEST_STATE_RUNNING
        ]
        assert running_prio, "storm placed nothing"
        # No priority-100 request may be left pending while ANY
        # priority-0 request of the same or larger footprint runs —
        # check the coarse version: some 100s run, and if any 100 is
        # pending then the cluster is genuinely full for its demand.
        if 100 in pending_prio:
            used = sim.live_used()
            free_hosts = sum(
                1 for n in sim.store.list(Node)
                if n.status.tpu_slots - used.get(n.metadata.name, 0) >= 4
            )
            pending_100 = [
                r for r in sim.store.list(ComposabilityRequest)
                if r.status.state != REQUEST_STATE_RUNNING
                and r.spec.priority == 100
            ]
            for r in pending_100:
                need = max(1, r.spec.resource.size // 4)
                assert free_hosts < need, (
                    f"{r.metadata.name} starved with {free_hosts} free hosts"
                )
