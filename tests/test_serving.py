"""Continuous-batching engine — the gold contract is solo-run equality.

Whatever the batch composition, admission order, slot reuse, or pool
pressure, every request's tokens must EQUAL what a solo decode.generate
call on its prompt produces. These tests stage churn deliberately:
staggered arrivals, lengths that finish mid-flight, more requests than
slots, and a pool sized to force head-of-line waiting.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_composer.models import ModelConfig
from tpu_composer.models.decode import generate
from tpu_composer.models.moe import MoEConfig
from tpu_composer.models.serving import ContinuousBatchingEngine
from tpu_composer.models.transformer import init_params


def _cfg():
    return ModelConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=64, max_seq=128,
                       dtype=jnp.float32)


def _solo(p, c, prompt, n):
    out = generate(p, jnp.asarray([prompt], jnp.int32), c,
                   max_new_tokens=n)
    return np.asarray(out)[0].tolist()


@pytest.fixture(scope="module")
def world():
    c = _cfg()
    p = init_params(c, jax.random.key(0))
    return c, p


class TestSoloEquality:
    def test_interleaved_requests_match_solo_runs(self, world):
        c, p = world
        key = jax.random.key(1)
        prompts = []
        for i in range(6):
            key, k = jax.random.split(key)
            ln = int(jax.random.randint(k, (), 3, 12))
            key, k = jax.random.split(key)
            prompts.append(
                np.asarray(jax.random.randint(
                    k, (ln,), 0, c.vocab_size)).tolist()
            )
        lens = [5, 9, 3, 12, 7, 4]  # finish at different times
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=32,
                                       block_size=8)
        reqs = [eng.submit(pr, n) for pr, n in zip(prompts, lens)]
        eng.run()
        for req, pr, n in zip(reqs, prompts, lens):
            assert req.done
            assert req.tokens == _solo(p, c, pr, n), (
                f"request {req.req_id} diverged from its solo run"
            )

    def test_single_slot_serializes_but_stays_exact(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=8,
                                       block_size=8)
        prompts = [[1, 2, 3], [7, 8], [5, 5, 5, 5]]
        reqs = [eng.submit(pr, 6) for pr in prompts]
        eng.run()
        for req, pr in zip(reqs, prompts):
            assert req.tokens == _solo(p, c, pr, 6)

    def test_pool_pressure_delays_but_never_corrupts(self, world):
        c, p = world
        # Pool fits ~one worst-case request at a time even though two
        # slots exist: the second must wait for blocks, then still match.
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=4,
                                       block_size=8)
        reqs = [eng.submit([3, 1, 4, 1, 5], 8) for _ in range(3)]
        eng.run()
        gold = _solo(p, c, [3, 1, 4, 1, 5], 8)
        for req in reqs:
            assert req.tokens == gold

    def test_eos_releases_early(self, world):
        c, p = world
        gold = _solo(p, c, [2, 7, 1], 10)
        # Truncation AT the first eos occurrence, whatever the model
        # repeats: eos = the first token cuts after exactly one.
        first_at = gold.index(gold[0])
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                       block_size=8, eos_id=gold[0])
        req = eng.submit([2, 7, 1], 10)
        eng.run()
        assert req.tokens == gold[:first_at + 1]
        assert int(eng.cache.free_top) == 16  # early release returned blocks
        # And an eos the model never emits changes nothing.
        absent = next(t for t in range(c.vocab_size) if t not in gold)
        eng2 = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                        block_size=8, eos_id=absent)
        req2 = eng2.submit([2, 7, 1], 10)
        eng2.run()
        assert req2.tokens == gold

    def test_pallas_kernel_path_matches(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                       block_size=8, attn_impl="pallas")
        reqs = [eng.submit([9, 8, 7], 5), eng.submit([1, 2], 7)]
        eng.run()
        assert reqs[0].tokens == _solo(p, c, [9, 8, 7], 5)
        assert reqs[1].tokens == _solo(p, c, [1, 2], 7)


class TestChunkedAdmission:
    def test_chunked_prefill_requests_match_solo_runs(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        prompts = [list(range(1, 21)), [5] * 11, [7, 9]]  # 3, 2, 1 chunks
        reqs = [eng.submit(pr, 6) for pr in prompts]
        eng.run()
        for req, pr in zip(reqs, prompts):
            assert req.tokens == _solo(p, c, pr, 6), (
                f"chunk-admitted request {req.req_id} diverged"
            )

    def test_admission_streams_while_others_decode(self, world):
        """The admission-latency contract: while a long prompt streams in
        chunk by chunk, an in-flight request keeps producing a token
        every step — admission never pauses the batch for more than one
        chunk."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        first = eng.submit([3, 1, 4], 12)
        eng.step()  # admits (first token) + one decode token
        assert len(first.tokens) == 2
        long = eng.submit(list(range(1, 25)), 4)  # 3 chunks
        for _ in range(3):  # the three admission-streaming steps
            before = len(first.tokens)
            eng.step()
            assert len(first.tokens) == before + 1, (
                "decode stalled during chunked admission"
            )
        assert len(long.tokens) >= 1  # admission finished, first token out
        eng.run()
        assert first.tokens == _solo(p, c, [3, 1, 4], 12)
        assert long.tokens == _solo(p, c, list(range(1, 25)), 4)

    def test_free_slots_admit_during_long_admission(self, world):
        """Round-robin admission: a long prompt streaming in must not
        leave other free slots idle — short requests admit and stream
        concurrently (one chunk of admission work per step total)."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=48,
                                       block_size=8, prefill_chunk=8)
        long = eng.submit(list(range(1, 49)), 3)   # 6 chunks
        short1 = eng.submit([4, 2], 3)             # 1 chunk
        short2 = eng.submit([7, 7, 7], 3)          # 1 chunk
        # After six steps (round-robin: L,L,S1,L,S2,L) both shorts must
        # be producing tokens while the long admission still streams.
        for _ in range(6):
            eng.step()
        assert not long.tokens  # still streaming (6 chunks, 1/step)
        assert short1.tokens and short2.tokens
        eng.run()
        assert long.tokens == _solo(p, c, list(range(1, 49)), 3)
        assert short1.tokens == _solo(p, c, [4, 2], 3)
        assert short2.tokens == _solo(p, c, [7, 7, 7], 3)

    def test_chunked_sampled_and_int8(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8,
                                       kv_quant=True)
        pr = list(range(2, 15))
        req = eng.submit(pr, 7, temperature=0.7, top_k=6, seed=21)
        eng.run()
        gold = np.asarray(generate(
            p, jnp.asarray([pr], jnp.int32), c, max_new_tokens=7,
            temperature=0.7, top_k=6, key=jax.random.key(21),
            kv_quant=True))[0].tolist()
        assert req.tokens == gold


class TestSampling:
    def _solo_sampled(self, p, c, prompt, n, temperature, top_k, top_p,
                      seed):
        out = generate(p, jnp.asarray([prompt], jnp.int32), c,
                       max_new_tokens=n, temperature=temperature,
                       top_k=top_k or None,
                       top_p=top_p if top_p < 1.0 else None,
                       key=jax.random.key(seed))
        return np.asarray(out)[0].tolist()

    def test_sampled_request_matches_solo_run(self, world):
        """The whole point of the per-request key schedule: a sampled
        request equals generate() with the same controls and key."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                       block_size=8)
        cases = [
            ([5, 9, 2], 8, 0.8, 5, 0.9, 7),
            ([1, 3], 6, 1.3, 0, 1.0, 11),   # pure temperature
            ([8, 8, 8, 8], 7, 0.5, 3, 1.0, 3),  # top-k only
        ]
        reqs = [eng.submit(pr, n, temperature=t, top_k=k, top_p=pp,
                           seed=s) for pr, n, t, k, pp, s in cases]
        eng.run()
        for req, (pr, n, t, k, pp, s) in zip(reqs, cases):
            assert req.tokens == self._solo_sampled(p, c, pr, n, t, k,
                                                    pp, s), (
                f"sampled request {req.req_id} diverged from its solo run"
            )

    def test_mixed_greedy_and_sampled_slots(self, world):
        """Greedy and sampled requests share one jitted step; neither
        may perturb the other."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=24,
                                       block_size=8)
        g = eng.submit([2, 4, 6], 7)
        s1 = eng.submit([3, 5], 7, temperature=0.9, top_k=4, seed=13)
        s2 = eng.submit([9, 1, 1], 5, temperature=1.1, top_p=0.8, seed=5)
        eng.run()
        assert g.tokens == _solo(p, c, [2, 4, 6], 7)
        assert s1.tokens == self._solo_sampled(p, c, [3, 5], 7, 0.9, 4,
                                               1.0, 13)
        assert s2.tokens == self._solo_sampled(p, c, [9, 1, 1], 5, 1.1,
                                               0, 0.8, 5)

    def test_submit_validates_sampling_controls(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=8,
                                       block_size=8)
        with pytest.raises(ValueError, match="top_k"):
            eng.submit([1], 2, top_k=-1)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1], 2, top_p=0.0)


class TestPrefixCaching:
    def test_shared_prefix_requests_match_solo_runs(self, world):
        """The system-prompt cache: requests attached to one registered
        prefix must produce EXACTLY their solo-run tokens — the shared
        blocks hold the same K/V a solo prefill would compute (absolute
        RoPE positions; identical prefix => identical K/V)."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=48,
                                       block_size=8, prefill_chunk=8)
        sys_prompt = list(range(1, 17))  # 16 tokens = 2 blocks
        h = eng.register_prefix(sys_prompt)
        free_after_reg = int(eng.cache.free_top)
        tails = [[7, 3], [9], [5, 5, 5, 2]]
        reqs = [eng.submit(sys_prompt + t, 6, prefix=h) for t in tails]
        eng.run()
        for req, t in zip(reqs, tails):
            assert req.tokens == _solo(p, c, sys_prompt + t, 6), (
                f"prefix-attached request {req.req_id} diverged"
            )
        # Shared blocks stayed in the pool (held by the handle), every
        # per-request block came back.
        assert int(eng.cache.free_top) == free_after_reg
        eng.close_prefix(h)
        assert int(eng.cache.free_top) == 48  # prefix freed at last drop
        assert sorted(np.asarray(eng.cache.free).tolist()) == list(range(48))

    def test_prefix_is_cached_once(self, world):
        """The memory claim: N attached requests hold ONE copy of the
        prefix blocks — admission pops only per-request suffix blocks."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        h = eng.register_prefix(list(range(1, 25)))  # 24 tokens = 3 blocks
        free0 = int(eng.cache.free_top)
        r1 = eng.submit(h.tokens + [4], 12, prefix=h)
        r2 = eng.submit(h.tokens + [6], 12, prefix=h)
        eng.step()  # admits r1 (attach + its one chunk)
        eng.step()  # admits r2; both now mid-flight
        assert not r1.done and not r2.done
        # Each attached row claimed only its OWN suffix blocks: pool
        # usage is free0 minus fresh blocks, not minus 2x prefix.
        used = free0 - int(eng.cache.free_top)
        assert used <= 2 * 3  # <= two rows' worth of suffix+decode blocks
        # The prefix blocks are co-owned: refcount = handle + attached.
        rc = np.asarray(eng.cache.refcount)[np.asarray(h.block_ids)]
        assert (rc == 3).all()  # handle + two in-flight rows
        eng.run()
        rc = np.asarray(eng.cache.refcount)[np.asarray(h.block_ids)]
        assert (rc == 1).all()  # rows done: only the handle holds them
        eng.close_prefix(h)
        assert int(eng.cache.free_top) == 32

    def test_prefix_with_sampling_and_cancel(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        h = eng.register_prefix(list(range(2, 10)))  # 8 tokens
        sampled = eng.submit(h.tokens + [3, 1], 5, temperature=0.9,
                             top_k=4, seed=17, prefix=h)
        doomed = eng.submit(h.tokens + [9], 8, prefix=h)
        eng.step(); eng.step()
        eng.cancel(doomed)
        eng.run()
        gold = np.asarray(generate(
            p, jnp.asarray([h.tokens + [3, 1]], jnp.int32), c,
            max_new_tokens=5, temperature=0.9, top_k=4,
            key=jax.random.key(17)))[0].tolist()
        assert sampled.tokens == gold
        eng.close_prefix(h)
        assert int(eng.cache.free_top) == 32

    def test_close_while_request_queued_keeps_blocks_alive(self, world):
        """The review-caught lifecycle hole: closing a handle while a
        prefix request still WAITS (holding no pool refcount) must not
        free the blocks — a decoding row would recycle them and the
        queued request would attach to foreign K/V. The handle's host
        refs keep the registry hold until the last request finishes."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        h = eng.register_prefix(list(range(1, 9)))
        hog = eng.submit([2, 4, 6], 10)       # takes the only slot
        queued = eng.submit(h.tokens + [5, 5], 6, prefix=h)
        eng.step()  # hog admitted; queued waits
        assert not queued.tokens
        eng.close_prefix(h)
        # The prefix blocks must still be held (refcount >= 1): the
        # queued request's host-side reference pins them.
        rc = np.asarray(eng.cache.refcount)[np.asarray(h.block_ids)]
        assert (rc >= 1).all()
        eng.run()
        assert queued.tokens == _solo(p, c, h.tokens + [5, 5], 6)
        # Last reference gone -> blocks freed without close being called
        # again.
        assert int(eng.cache.free_top) == 32

    def test_prefix_validation(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=16,
                                       block_size=8, prefill_chunk=8)
        with pytest.raises(ValueError, match="multiple of"):
            eng.register_prefix([1, 2, 3])  # not block-aligned
        h = eng.register_prefix(list(range(1, 9)))
        with pytest.raises(ValueError, match="START with"):
            eng.submit([9, 9, 9, 9, 9, 9, 9, 9, 1], 2, prefix=h)
        with pytest.raises(ValueError, match="START with"):
            eng.submit(h.tokens, 2, prefix=h)  # no suffix
        eng.close_prefix(h)
        with pytest.raises(ValueError, match="closed"):
            eng.submit(h.tokens + [1], 2, prefix=h)
        # Bucketed engines reject prefix attachment outright.
        eng2 = ContinuousBatchingEngine(p, c, slots=1, num_blocks=16,
                                        block_size=8)
        with pytest.raises(ValueError, match="chunked admission"):
            eng2.submit([1, 2], 2, prefix=h)

    def test_register_prefix_rejected_on_bucketed_engine(self, world):
        """Registration must fail where attachment would: a bucketed
        engine (no prefill_chunk) can never submit against a prefix, so a
        registered one would hold pool blocks forever."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=16,
                                       block_size=8)
        free_before = int(eng.cache.free_top)
        with pytest.raises(ValueError, match="chunked admission"):
            eng.register_prefix(list(range(1, 9)))
        assert int(eng.cache.free_top) == free_before  # nothing leaked


class TestCancellation:
    def test_cancel_in_every_lifecycle_stage(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                       block_size=8, prefill_chunk=8)
        decoding = eng.submit([1, 2, 3], 10)
        streaming = eng.submit(list(range(1, 25)), 5)  # 3 chunks
        waiting = eng.submit([7], 5)  # no slot: both are taken
        eng.step()  # admits `decoding` (its one chunk)
        eng.step()  # decoding's first token; `streaming` starts chunk 1
        eng.step()  # streaming mid-admission (chunk 2 of 3)
        assert decoding.tokens
        # Genuinely mid-admission when cancelled — not merely waiting.
        assert any(st["req"] is streaming for st in eng._admitting)
        assert eng.cancel(waiting) and waiting.done
        assert eng.cancel(streaming) and streaming.done
        assert not streaming.tokens  # never produced anything
        assert not any(st["req"] is streaming for st in eng._admitting)
        assert eng.cancel(decoding) and decoding.done
        assert eng.cancel(decoding) is False  # double-cancel is a no-op
        eng.run()
        assert int(eng.cache.free_top) == 16  # every block returned

    def test_cancel_frees_slot_for_next_request(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=8,
                                       block_size=8)
        hog = eng.submit([5, 5], 40)  # holds 6 of the 8 blocks
        eng.step()
        eng.cancel(hog)
        nxt = eng.submit([3, 1, 4], 4)
        eng.run()
        assert nxt.tokens == _solo(p, c, [3, 1, 4], 4)


class TestChurnStorm:
    def test_random_churn_conserves_and_stays_exact(self, world):
        """Serving soak: random submits, cancels and drains across modes
        (greedy/sampled, short/long prompts) — the pool must conserve
        blocks and surviving requests must still equal their solo runs."""
        import random

        c, p = world
        rng = random.Random(42)
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=48,
                                       block_size=8, prefill_chunk=8)
        live, finished = [], []
        for i in range(60):
            r = rng.random()
            if r < 0.4 and len(live) < 8:
                ln = rng.randint(1, 20)
                pr = [rng.randint(0, c.vocab_size - 1)
                      for _ in range(ln)]
                if rng.random() < 0.3:
                    req = eng.submit(pr, rng.randint(1, 6),
                                     temperature=0.8, top_k=5,
                                     seed=rng.randint(0, 99))
                else:
                    req = eng.submit(pr, rng.randint(1, 6))
                req._prompt_copy = list(pr)
                live.append(req)
            elif r < 0.5 and live:
                eng.cancel(live.pop(rng.randrange(len(live))))
            else:
                eng.step()
            finished += [q for q in live if q.done]
            live = [q for q in live if not q.done]
        eng.run()
        finished += live
        assert int(eng.cache.free_top) == 48
        assert sorted(np.asarray(eng.cache.free).tolist()) == list(range(48))
        # Spot-check solo equality on the greedy survivors.
        for req in [q for q in finished if q.temperature == 0
                    and q.tokens][:5]:
            assert req.tokens == _solo(p, c, req._prompt_copy,
                                       req.max_new_tokens)[:len(req.tokens)]


class TestEngineHygiene:
    def test_pool_drains_back_to_full(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=3, num_blocks=24,
                                       block_size=8)
        for i in range(7):
            eng.submit([i + 1, i + 2], 4)
        eng.run()
        assert int(eng.cache.free_top) == 24
        assert sorted(np.asarray(eng.cache.free).tolist()) == list(range(24))

    def test_rejects_impossible_request(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=2,
                                       block_size=8)
        with pytest.raises(ValueError, match="worst-case"):
            eng.submit(list(range(30)), 20)

    def test_moe_requires_chunked_admission(self, world):
        c, p = world
        mc = MoEConfig(vocab_size=64, d_model=32, n_layers=1, n_heads=4,
                       n_kv_heads=2, d_ff=64, max_seq=64,
                       dtype=jnp.float32, n_experts=2, top_k=1)
        with pytest.raises(ValueError, match="chunked admission"):
            ContinuousBatchingEngine(p, mc, slots=1, num_blocks=4)

    def test_moe_serves_exactly_via_chunked_admission(self):
        """MoE through the engine: chunked admission routes with
        drop-free decode-chunk capacity, so chunk pads cannot displace
        real tokens. Solo equality is CONDITIONAL the way decode.py
        documents for chunked verification — it holds when the solo
        prefill itself drops nothing — so this test pins it in the
        drop-free regime (generous capacity_factor: capacity(S) >= S for
        every prompt here). Under saturation the engine's drop-free
        routing is deliberately the more faithful serving computation."""
        from tpu_composer.models.moe import init_params as init_moe

        mc = MoEConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=64, max_seq=128,
                       dtype=jnp.float32, n_experts=4, top_k=2,
                       capacity_factor=4.0)
        mp = init_moe(mc, jax.random.key(3))
        eng = ContinuousBatchingEngine(mp, mc, slots=2, num_blocks=32,
                                       block_size=8, prefill_chunk=8)
        prompts = [list(range(1, 14)), [9, 9, 9], [4, 5, 6, 7, 8]]
        reqs = [eng.submit(pr, 6) for pr in prompts]
        eng.run()
        for req, pr in zip(reqs, prompts):
            gold = np.asarray(generate(
                mp, jnp.asarray([pr], jnp.int32), mc,
                max_new_tokens=6))[0].tolist()
            assert req.tokens == gold, (
                f"MoE request {req.req_id} diverged from its solo run"
            )

    def test_submit_validates_with_scheduler_math(self, world):
        """A request submit() accepts must be schedulable: validation
        uses the bucketed prompt length the scheduler reserves with —
        raw-length validation would accept a request _try_admit can
        never place, livelocking the FIFO head-of-line."""
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=3,
                                       block_size=8)
        # 17 tokens bucket to 32; ceil((32+7)/8)=5 > 3 blocks -> reject
        # at submit, not livelock at run.
        with pytest.raises(ValueError, match="worst-case"):
            eng.submit(list(range(1, 18)), 7)

    def test_step_events_include_the_prefill_token(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=8,
                                       block_size=8)
        req = eng.submit([4, 2], 1)  # one token: comes from the prefill
        events = eng.step()
        assert events == [(req.req_id, req.tokens[0])]
        assert req.done
        # Streaming a longer request: concatenating every step's events
        # reproduces the full output, first token included.
        req2 = eng.submit([4, 2], 5)
        seen = []
        while not req2.done:
            seen.extend(t for rid, t in eng.step() if rid == req2.req_id)
        assert seen == req2.tokens == _solo(p, c, [4, 2], 5)

    def test_blocks_per_row_bounds_the_table(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=64,
                                       block_size=8, blocks_per_row=4)
        assert eng.cache.block_tables.shape == (2, 4)
        reqs = [eng.submit([1, 2, 3], 6), eng.submit([9], 4)]
        eng.run()
        assert reqs[0].tokens == _solo(p, c, [1, 2, 3], 6)
        assert reqs[1].tokens == _solo(p, c, [9], 4)
        # A request beyond the per-row table is rejected up front even
        # though the pool has plenty of blocks.
        with pytest.raises(ValueError, match="positions per row"):
            eng.submit(list(range(1, 30)), 10)

    def test_int8_engine_matches_dense_int8(self, world):
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                       block_size=8, kv_quant=True)
        reqs = [eng.submit([3, 1, 4], 6), eng.submit([2, 7], 5)]
        eng.run()
        gold0 = np.asarray(generate(
            p, jnp.asarray([[3, 1, 4]], jnp.int32), c, max_new_tokens=6,
            kv_quant=True))[0].tolist()
        gold1 = np.asarray(generate(
            p, jnp.asarray([[2, 7]], jnp.int32), c, max_new_tokens=5,
            kv_quant=True))[0].tolist()
        assert reqs[0].tokens == gold0
        assert reqs[1].tokens == gold1
        # int8 pool + the Pallas kernel path: same solo equality.
        eng2 = ContinuousBatchingEngine(p, c, slots=2, num_blocks=16,
                                        block_size=8, kv_quant=True,
                                        attn_impl="pallas")
        req2 = eng2.submit([3, 1, 4], 6)
        eng2.run()
        assert req2.tokens == gold0

    def test_compiles_are_bucketed(self, world):
        # Same bucket -> same prefill shape -> one compile in jit's
        # shape-keyed cache; the engine must not compile per prompt length.
        c, p = world
        eng = ContinuousBatchingEngine(p, c, slots=2, num_blocks=32,
                                       block_size=8)
        for ln in (3, 5, 7, 8):  # all bucket to 8
            eng.submit(list(range(1, ln + 1)), 2)
        eng.run()
        assert eng._prefill._cache_size() == 1

    def test_bucket_padding_does_not_shrink_max_seq(self, world):
        """Regression (review-caught): a 65-token prompt buckets to 128 =
        max_seq, but RoPE positions advance from the REAL length — the
        request is valid (65 + 10 <= 128) and must serve, token-equal to
        its solo run."""
        c, p = world  # max_seq = 128
        prompt = list(range(1, 66))  # 65 tokens -> pad 128
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=64,
                                       block_size=8)
        req = eng.submit(prompt, 10)
        eng.run()
        assert req.tokens == _solo(p, c, prompt, 10)

    def test_rejects_beyond_max_seq(self, world):
        # The gold reference (solo decode.generate) raises past
        # config.max_seq; a request with no defined gold output must be
        # rejected at submit, not served.
        c, p = world  # max_seq=128
        eng = ContinuousBatchingEngine(p, c, slots=1, num_blocks=64,
                                       block_size=8)
        with pytest.raises(ValueError, match="max_seq"):
            eng.submit(list(range(1, 121)), 20)  # raw 120 + 20 > max_seq 128
