"""Shard-failover chaos soak (ISSUE 9 headline test).

Three full operator replicas share one in-proc store and one fabric pool,
each owning a balanced subset of K shard leases. One replica is hard-killed
(SIGKILL analog: store writes stop landing mid-stream, its dispatcher
abandons lanes, no lease is ever released) in the middle of a 32-chip
attach wave. The soak asserts the whole robustness contract:

- survivors CAS-steal the orphaned shard leases within ~one lease duration
  (observation-clock expiry + one tick of detection granularity),
- every shard acquisition runs the PR 5 adoption pass SCOPED to that
  shard's keys (a shard migration is a cold-start adoption over the moved
  keys), and the wave converges Ready,
- the nonce-checked zero-double-attach invariant from test_crash_restart
  holds across the handoff,
- no fabric mutation from the dead replica's identity lands after its
  monotonic fencing deadline (split-brain containment),
- attach-budget / quarantine accounting is bit-identical to an
  uninterrupted run (all zeros — no fabric fault was injected).

A second scenario proves the REBALANCE path: a replica joining mid-wave is
handed shards via shed + scoped adoption with the same invariants.

Run: ``make shard-soak`` (markers slow+shard).
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import REQUEST_STATE_RUNNING
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    RequestTiming,
    ResourceTiming,
    UpstreamSyncer,
)
from tpu_composer.controllers.adoption import adopt_pending_ops
from tpu_composer.fabric.dispatcher import FabricDispatcher
from tpu_composer.runtime.cache import CachedClient
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.shards import ShardLeaseElector, shard_for
from tpu_composer.runtime.store import Store

from tests.test_crash_restart import (
    CrashFuse,
    RecordingPool,
    assert_no_double_attach,
    wait_for,
)

LEASE_S = 2.0
RENEW_S = 0.25


class TaggedPool:
    """Per-replica fabric facade over the shared pool: every MUTATING verb
    is logged with (replica identity, monotonic timestamp) before it runs,
    so the soak can assert no mutation from a dead replica's identity
    lands after its fencing deadline."""

    def __init__(self, pool, identity, mutation_log):
        self._pool = pool
        self._identity = identity
        self._log = mutation_log

    def _tag(self, verb, names):
        self._log.append((self._identity, time.monotonic(), verb, names))

    def add_resource(self, resource):
        self._tag("add", [resource.metadata.name])
        return self._pool.add_resource(resource)

    def remove_resource(self, resource):
        self._tag("remove", [resource.metadata.name])
        return self._pool.remove_resource(resource)

    def add_resources(self, resources):
        self._tag("add", [r.metadata.name for r in resources])
        return self._pool.add_resources(resources)

    def remove_resources(self, resources):
        self._tag("remove", [r.metadata.name for r in resources])
        return self._pool.remove_resources(resources)

    def repair_slice_member(self, *a, **kw):
        self._tag("repair", [])
        return self._pool.repair_slice_member(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.__dict__["_pool"], name)


class ShardedReplica:
    """One operator replica: CrashFuse store facade + cached client +
    dispatcher + shard elector, wired exactly like cmd/main does for
    --shards K (scoped adoption on acquire, resync on ready, lane fence
    on lose)."""

    def __init__(self, raw_store, pool, ident, num_shards, mutation_log,
                 reports, expected_replicas=0):
        self.ident = ident
        self.fuse = CrashFuse(raw_store)
        self.client = CachedClient(self.fuse)
        self.tagged = TaggedPool(pool, ident, mutation_log)
        self.elector = ShardLeaseElector(
            self.fuse, num_shards, identity=ident,
            lease_duration_s=LEASE_S, renew_period_s=RENEW_S,
            expected_replicas=expected_replicas,
        )
        own = self.elector.ownership
        self.dispatcher = FabricDispatcher(
            self.tagged, batch_window=0.01, concurrency=4,
            poll_interval=0.05, owns=own.owns_key,
        )
        self.mgr = Manager(store=self.client, leader_elector=self.elector,
                           dispatcher=self.dispatcher,
                           drain_timeout=0.0)  # crash harness: never drain
        self.elector.on_acquire.append(
            lambda wins: reports.append((ident, dict(wins),
                adopt_pending_ops(self.client, self.tagged, self.dispatcher,
                                  shards=set(wins), num_shards=num_shards))))
        self.elector.on_ready.append(
            lambda shards: self.mgr.resync(
                lambda key, _s=frozenset(shards):
                shard_for(key, num_shards) in _s))
        self.elector.on_lose.append(
            lambda shard, reason: self.dispatcher.abandon_unowned())
        self.mgr.add_controller(ComposabilityRequestReconciler(
            self.client, self.tagged,
            timing=RequestTiming(updating_poll=0.05, cleaning_poll=0.05),
            ownership=own))
        self.mgr.add_controller(ComposableResourceReconciler(
            self.client, self.tagged, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.05,
                                  detach_poll=0.05, detach_fast=0.05,
                                  busy_poll=0.05),
            dispatcher=self.dispatcher, ownership=own))
        self.mgr.add_runnable(UpstreamSyncer(
            self.client, self.tagged, period=0.1, grace=5.0, ownership=own))
        self.mgr.add_runnable(self.dispatcher.run)

    def start(self):
        self.mgr.start(workers_per_controller=2)

    def owned(self):
        return self.elector.owned_shards()

    def kill(self):
        """SIGKILL analog: writes stop landing, the dispatcher abandons
        lanes and parked outcomes, the renew thread dies — no lease is
        released; failover happens only through observation expiry."""
        self.fuse.die()
        self.dispatcher.kill()
        self.elector._stop.set()

    def stop(self):
        try:
            self.mgr.stop()
        except Exception:
            pass  # dead store: release can't land, like a real crash


def _world(nodes=8, slots=4):
    store = Store()
    for i in range(nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = slots
        store.create(n)
    return store


def _submit_wave(store, name="wave", size=32):
    store.create(ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(resource=ResourceDetails(
            type="tpu", model="tpu-v4", size=size)),
    ))


def _running(store, name, size):
    req = store.try_get(ComposabilityRequest, name)
    return (
        req is not None
        and req.status.state == REQUEST_STATE_RUNNING
        and sum(len(r.device_ids)
                for r in req.status.resources.values()) == size
    )


def _assert_clean_accounting(store, pool, attached):
    for res in store.list(ComposableResource):
        assert res.status.pending_op is None, res.status.to_dict()
        assert res.status.attach_attempts == 0, res.status.to_dict()
        assert not res.status.quarantined, res.status.to_dict()
    assert len(pool.get_resources()) == attached
    assert pool.free_chips("tpu-v4") == 64 - attached  # no leak, no double
    assert_no_double_attach(pool.events)


@pytest.mark.slow
@pytest.mark.shard
class TestShardFailoverSoak:
    K = 6
    REPLICAS = 3

    def test_kill_minus_nine_mid_wave(self):
        for cycle, kill_delay in enumerate((0.0, 0.15)):
            self._one_cycle(cycle, kill_delay)

    def _one_cycle(self, cycle, kill_delay):
        store = _world()
        pool = RecordingPool(async_steps=2)
        mutations = []
        reports = []
        replicas = [
            ShardedReplica(store, pool, f"replica-{cycle}-{i}", self.K,
                           mutations, reports,
                           expected_replicas=self.REPLICAS)
            for i in range(self.REPLICAS)
        ]
        try:
            for r in replicas:
                r.start()
            # Balanced steady state: every shard owned exactly once.
            assert wait_for(
                lambda: sorted(
                    s for r in replicas for s in r.owned()
                ) == list(range(self.K)),
                timeout=3 * LEASE_S,
            ), f"shards never balanced: {[r.owned() for r in replicas]}"

            _submit_wave(store, size=32)
            # Mid-wave: durable attach intent on the wire, fabric-async
            # steps still pending — the widest in-flight window.
            assert wait_for(
                lambda: sum(
                    1 for res in store.list(ComposableResource)
                    if res.status.pending_op is not None
                ) >= 2,
                timeout=15,
            ), "no pending_op intents ever persisted — kill missed the wave"
            time.sleep(kill_delay)

            victim = replicas[0]
            assert victim.owned(), "victim held no shards — nothing to test"
            orphaned = set(victim.owned())
            t_kill = time.monotonic()
            victim.kill()
            fence_deadline = t_kill + victim.elector.renew_deadline_s

            survivors = replicas[1:]

            def survivors_own_everything():
                held = [s for r in survivors for s in r.owned()]
                return sorted(held) == list(range(self.K))

            assert wait_for(survivors_own_everything, timeout=4 * LEASE_S), (
                "survivors never acquired the orphaned shards:"
                f" {[r.owned() for r in survivors]}"
            )
            takeover_s = time.monotonic() - t_kill
            # Observation-clock failover: expiry at ~(last observed renew
            # + lease), detection within a tick — one lease duration plus
            # tick granularity and CI scheduling slack.
            assert takeover_s <= LEASE_S + 4 * RENEW_S + 1.0, (
                f"takeover took {takeover_s:.2f}s (lease {LEASE_S}s)"
            )
            # No shard is double-owned across survivors.
            assert not (survivors[0].owned() & survivors[1].owned())
            # Scoped adoption ran for the stolen shards.
            stolen_adoptions = [
                (ident, shard)
                for ident, wins, _ in reports
                for shard, reason in wins.items()
                if reason == "failover" and shard in orphaned
            ]
            assert stolen_adoptions, "no scoped adoption pass on failover"

            assert wait_for(
                lambda: _running(store, "wave", 32), timeout=60,
            ), "wave never converged Ready after shard failover: " + repr([
                (r.metadata.name, r.status.state,
                 r.status.pending_op is not None)
                for r in store.list(ComposableResource)])
            _assert_clean_accounting(store, pool, attached=32)

            # Fencing: nothing from the dead replica's identity may touch
            # the fabric after its monotonic fencing deadline.
            late = [
                m for m in mutations
                if m[0] == victim.ident and m[1] > fence_deadline
            ]
            assert not late, (
                f"dead replica mutated the fabric after its fencing"
                f" deadline: {late}"
            )
        finally:
            for r in replicas:
                r.kill()
                r.stop()

    def test_rebalance_handoff_mid_wave(self):
        """A replica joining mid-wave is HANDED shards: the incumbent
        sheds (fence + lease release), the newcomer adopts scoped — the
        wave must converge with zero double-attach, exactly like
        failover but through the voluntary path."""
        store = _world(nodes=4)
        pool = RecordingPool(async_steps=2)
        mutations = []
        reports = []
        a = ShardedReplica(store, pool, "incumbent", 4, mutations, reports)
        try:
            a.start()
            assert wait_for(lambda: a.owned() == {0, 1, 2, 3},
                            timeout=2 * LEASE_S)
            _submit_wave(store, size=16)
            assert wait_for(
                lambda: any(res.status.pending_op is not None
                            for res in store.list(ComposableResource)),
                timeout=15,
            ), "kill missed the wave"
            b = ShardedReplica(store, pool, "newcomer", 4, mutations, reports)
            try:
                b.start()
                assert wait_for(
                    lambda: len(b.owned()) >= 1
                    and len(a.owned()) + len(b.owned()) == 4
                    and not (a.owned() & b.owned()),
                    timeout=6 * LEASE_S,
                ), f"rebalance never handed shards over: a={a.owned()} b={b.owned()}"
                handed = [
                    (ident, shard)
                    for ident, wins, _ in reports
                    if ident == "newcomer"
                    for shard, reason in wins.items()
                    if reason in ("handoff", "failover")
                ]
                assert handed, "newcomer never ran a scoped adoption pass"
                assert wait_for(
                    lambda: _running(store, "wave", 16), timeout=60,
                ), "wave never converged Ready after rebalance handoff"
                _assert_clean_accounting(store, pool, attached=16)
            finally:
                b.kill()
                b.stop()
        finally:
            a.kill()
            a.stop()
