"""Shard-failover chaos soak (ISSUE 9 headline test).

Three full operator replicas share one in-proc store and one fabric pool,
each owning a balanced subset of K shard leases. One replica is hard-killed
(SIGKILL analog: store writes stop landing mid-stream, its dispatcher
abandons lanes, no lease is ever released) in the middle of a 32-chip
attach wave. The soak asserts the whole robustness contract:

- survivors CAS-steal the orphaned shard leases within ~one lease duration
  (observation-clock expiry + one tick of detection granularity),
- every shard acquisition runs the PR 5 adoption pass SCOPED to that
  shard's keys (a shard migration is a cold-start adoption over the moved
  keys), and the wave converges Ready,
- the nonce-checked zero-double-attach invariant from test_crash_restart
  holds across the handoff,
- no fabric mutation from the dead replica's identity lands after its
  monotonic fencing deadline (split-brain containment),
- attach-budget / quarantine accounting is bit-identical to an
  uninterrupted run (all zeros — no fabric fault was injected),
- the failover renders as ONE stitched trace (ISSUE 12): partitioning the
  shared trace ring into per-replica files and running the trace-merge
  pass yields a pre-crash intent span (victim pid) and a post-crash adopt
  span (survivor pid) under one intent-nonce trace id, connected by a
  synthetic flow arrow across the two pids.

A second scenario proves the REBALANCE path: a replica joining mid-wave is
handed shards via shed + scoped adoption with the same invariants.

Run: ``make shard-soak`` (markers slow+shard).
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import REQUEST_STATE_RUNNING
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    RequestTiming,
    ResourceTiming,
    UpstreamSyncer,
)
from tpu_composer.controllers.adoption import adopt_pending_ops
from tpu_composer.fabric.dispatcher import FabricDispatcher
from tpu_composer.runtime import tracing
from tpu_composer.runtime.cache import CachedClient
from tpu_composer.runtime.fleet import FleetPlane
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.shards import ShardLeaseElector, shard_for
from tpu_composer.runtime.store import Store

from tests.test_crash_restart import (
    CrashFuse,
    RecordingPool,
    assert_no_double_attach,
    wait_for,
)

LEASE_S = 2.0
RENEW_S = 0.25


class TaggedPool:
    """Per-replica fabric facade over the shared pool: every MUTATING verb
    is logged with (replica identity, monotonic timestamp) before it runs,
    so the soak can assert no mutation from a dead replica's identity
    lands after its fencing deadline."""

    def __init__(self, pool, identity, mutation_log):
        self._pool = pool
        self._identity = identity
        self._log = mutation_log

    def _tag(self, verb, names):
        self._log.append((self._identity, time.monotonic(), verb, names))

    def add_resource(self, resource):
        self._tag("add", [resource.metadata.name])
        return self._pool.add_resource(resource)

    def remove_resource(self, resource):
        self._tag("remove", [resource.metadata.name])
        return self._pool.remove_resource(resource)

    def add_resources(self, resources):
        self._tag("add", [r.metadata.name for r in resources])
        return self._pool.add_resources(resources)

    def remove_resources(self, resources):
        self._tag("remove", [r.metadata.name for r in resources])
        return self._pool.remove_resources(resources)

    def repair_slice_member(self, *a, **kw):
        self._tag("repair", [])
        return self._pool.repair_slice_member(*a, **kw)

    def __getattr__(self, name):
        return getattr(self.__dict__["_pool"], name)


class ShardedReplica:
    """One operator replica: CrashFuse store facade + cached client +
    dispatcher + shard elector, wired exactly like cmd/main does for
    --shards K (scoped adoption on acquire, resync on ready, lane fence
    on lose)."""

    def __init__(self, raw_store, pool, ident, num_shards, mutation_log,
                 reports, expected_replicas=0):
        self.ident = ident
        self.fuse = CrashFuse(raw_store)
        self.client = CachedClient(self.fuse)
        self.tagged = TaggedPool(pool, ident, mutation_log)
        self.elector = ShardLeaseElector(
            self.fuse, num_shards, identity=ident,
            lease_duration_s=LEASE_S, renew_period_s=RENEW_S,
            expected_replicas=expected_replicas,
        )
        own = self.elector.ownership
        self.dispatcher = FabricDispatcher(
            self.tagged, batch_window=0.01, concurrency=4,
            poll_interval=0.05, owns=own.owns_key,
        )
        # Fleet plane per replica, on its own stop event so kill() can
        # end it the way a real SIGKILL would (a dead replica must stop
        # aggregating — its last view would fight the survivors' gauges).
        self.fleet = FleetPlane(
            self.fuse, identity=ident, num_shards=num_shards,
            ownership=own, publish_period=0.25, stale_after_s=2.0,
        )
        self._fleet_stop = threading.Event()
        self._fleet_thread = None
        self.mgr = Manager(store=self.client, leader_elector=self.elector,
                           dispatcher=self.dispatcher,
                           drain_timeout=0.0,  # crash harness: never drain
                           # Trace events carry the replica identity as
                           # their Chrome pid — what the stitch assertion
                           # partitions and merges on.
                           replica_id=ident, fleet=self.fleet)
        self.elector.on_acquire.append(
            lambda wins: reports.append((ident, dict(wins),
                adopt_pending_ops(self.client, self.tagged, self.dispatcher,
                                  shards=set(wins), num_shards=num_shards))))
        self.elector.on_ready.append(
            lambda shards: self.mgr.resync(
                lambda key, _s=frozenset(shards):
                shard_for(key, num_shards) in _s))
        self.elector.on_lose.append(
            lambda shard, reason: self.dispatcher.abandon_unowned())
        self.mgr.add_controller(ComposabilityRequestReconciler(
            self.client, self.tagged,
            timing=RequestTiming(updating_poll=0.05, cleaning_poll=0.05),
            ownership=own))
        self.mgr.add_controller(ComposableResourceReconciler(
            self.client, self.tagged, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.05,
                                  detach_poll=0.05, detach_fast=0.05,
                                  busy_poll=0.05),
            dispatcher=self.dispatcher, ownership=own))
        self.mgr.add_runnable(UpstreamSyncer(
            self.client, self.tagged, period=0.1, grace=5.0, ownership=own))
        self.mgr.add_runnable(self.dispatcher.run)

    def start(self):
        self.mgr.start(workers_per_controller=2)
        self._fleet_thread = threading.Thread(
            target=self.fleet.run, args=(self._fleet_stop,), daemon=True,
        )
        self._fleet_thread.start()

    def owned(self):
        return self.elector.owned_shards()

    def kill(self):
        """SIGKILL analog: writes stop landing, the dispatcher abandons
        lanes and parked outcomes, the renew thread dies — no lease is
        released; failover happens only through observation expiry. The
        fleet plane dies with the process: its snapshot's seq freezes in
        the store, which is exactly what the survivors' staleness clocks
        age out."""
        self.fuse.die()
        self.dispatcher.kill()
        self.elector._stop.set()
        self._fleet_stop.set()

    def stop(self):
        self._fleet_stop.set()
        try:
            self.mgr.stop()
        except Exception:
            pass  # dead store: release can't land, like a real crash


def _world(nodes=8, slots=4):
    store = Store()
    for i in range(nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = slots
        store.create(n)
    return store


def _submit_wave(store, name="wave", size=32):
    store.create(ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(resource=ResourceDetails(
            type="tpu", model="tpu-v4", size=size)),
    ))


def _running(store, name, size):
    req = store.try_get(ComposabilityRequest, name)
    return (
        req is not None
        and req.status.state == REQUEST_STATE_RUNNING
        and sum(len(r.device_ids)
                for r in req.status.resources.values()) == size
    )


def _assert_clean_accounting(store, pool, attached):
    for res in store.list(ComposableResource):
        assert res.status.pending_op is None, res.status.to_dict()
        assert res.status.attach_attempts == 0, res.status.to_dict()
        assert not res.status.quarantined, res.status.to_dict()
    assert len(pool.get_resources()) == attached
    assert pool.free_chips("tpu-v4") == 64 - attached  # no leak, no double
    assert_no_double_attach(pool.events)


@pytest.mark.slow
@pytest.mark.shard
class TestShardFailoverSoak:
    K = 6
    REPLICAS = 3

    def test_kill_minus_nine_mid_wave(self):
        # Two kill points, both pinned to observable in-flight state (not
        # wall-clock sleeps, which race the wave's completion): cycle 0
        # kills at the FIRST victim-shard intent, cycle 1 deeper into the
        # wave, with two victim-shard intents simultaneously in flight.
        for cycle, min_victim_pending in enumerate((1, 2)):
            self._one_cycle(cycle, min_victim_pending)

    def _one_cycle(self, cycle, min_victim_pending):
        store = _world()
        # async_steps=4 (vs the rebalance scenario's 2): each fabric op
        # stays pending for several dispatcher re-poll quanta, so the
        # "min_victim_pending intents simultaneously in flight" kill
        # condition is reliably reachable — with a faster fabric the
        # deeper (cycle 1) kill point can race the wave's completion.
        pool = RecordingPool(async_steps=4)
        mutations = []
        reports = []
        # Fresh, generous trace ring: the stitch assertion needs the
        # PRE-crash intent spans still resident after a worst-case
        # convergence tail — the default 10k ring could age them out.
        tracing.configure(200_000)
        replicas = [
            ShardedReplica(store, pool, f"replica-{cycle}-{i}", self.K,
                           mutations, reports,
                           expected_replicas=self.REPLICAS)
            for i in range(self.REPLICAS)
        ]
        try:
            for r in replicas:
                r.start()
            # Balanced steady state: every shard owned exactly once.
            assert wait_for(
                lambda: sorted(
                    s for r in replicas for s in r.owned()
                ) == list(range(self.K)),
                timeout=3 * LEASE_S,
            ), f"shards never balanced: {[r.owned() for r in replicas]}"

            _submit_wave(store, size=32)

            # Mid-wave: durable attach intent on the wire, fabric-async
            # steps still pending — the widest in-flight window. The
            # VICTIM is chosen dynamically as the replica owning the most
            # in-flight intents at the kill instant: the 32-chip wave
            # materializes as 8 node-children hashed across K=6 shards,
            # so a pre-chosen replica's shards sometimes hold none of
            # them — a fixed victim (or a second sequential wait; the
            # batched wave settles in bursts) flakes. Cycle 1 prefers a
            # DEEPER kill point (min_victim_pending intents in flight at
            # once) but degrades to any in-flight intent once a short
            # grace past submission has elapsed — the stranded-work
            # invariant is what matters, the depth is flavor.
            t_submit = time.monotonic()
            chosen = {}

            def kill_point():
                pending = [
                    res.metadata.name
                    for res in store.list(ComposableResource)
                    if res.status.pending_op is not None
                ]
                if not pending:
                    return False
                best, best_c = None, 0
                for r in replicas:
                    owned = r.owned()
                    c = sum(
                        1 for name in pending
                        if shard_for(name, self.K) in owned
                    )
                    if c > best_c:
                        best, best_c = r, c
                if best is None:
                    return False
                if best_c >= min_victim_pending or (
                    time.monotonic() - t_submit > 0.5
                ):
                    chosen["victim"] = best
                    return True
                return False

            assert wait_for(kill_point, timeout=15), (
                "no pending_op intent ever in flight on an owned shard"
                " — kill missed the wave"
            )
            victim = chosen["victim"]
            survivors = [r for r in replicas if r is not victim]

            assert victim.owned(), "victim held no shards — nothing to test"
            orphaned = set(victim.owned())
            t_kill = time.monotonic()
            victim.kill()
            fence_deadline = t_kill + victim.elector.renew_deadline_s

            def survivors_own_everything():
                held = [s for r in survivors for s in r.owned()]
                return sorted(held) == list(range(self.K))

            assert wait_for(survivors_own_everything, timeout=4 * LEASE_S), (
                "survivors never acquired the orphaned shards:"
                f" {[r.owned() for r in survivors]}"
            )
            takeover_s = time.monotonic() - t_kill
            # Observation-clock failover: expiry at ~(last observed renew
            # + lease), detection within a tick — one lease duration plus
            # tick granularity and CI scheduling slack.
            assert takeover_s <= LEASE_S + 4 * RENEW_S + 1.0, (
                f"takeover took {takeover_s:.2f}s (lease {LEASE_S}s)"
            )
            # No shard is double-owned across survivors.
            assert not (survivors[0].owned() & survivors[1].owned())
            # Scoped adoption ran for the stolen shards.
            stolen_adoptions = [
                (ident, shard)
                for ident, wins, _ in reports
                for shard, reason in wins.items()
                if reason == "failover" and shard in orphaned
            ]
            assert stolen_adoptions, "no scoped adoption pass on failover"

            assert wait_for(
                lambda: _running(store, "wave", 32), timeout=60,
            ), "wave never converged Ready after shard failover: " + repr([
                (r.metadata.name, r.status.state,
                 r.status.pending_op is not None)
                for r in store.list(ComposableResource)])
            _assert_clean_accounting(store, pool, attached=32)

            # Fencing: nothing from the dead replica's identity may touch
            # the fabric after its monotonic fencing deadline.
            late = [
                m for m in mutations
                if m[0] == victim.ident and m[1] > fence_deadline
            ]
            assert not late, (
                f"dead replica mutated the fabric after its fencing"
                f" deadline: {late}"
            )

            # Fleet view ages the corpse out: a survivor's aggregator must
            # mark the victim stale (seq frozen past the staleness window
            # on the survivor's OWN clock) and drop it from the live
            # count — the "dead replica can't pin fleet p99" satellite,
            # observed end-to-end through the kill.
            def victim_aged_out():
                view = survivors[0].fleet.snapshot()
                rep = view.get("replicas", {}).get(victim.ident)
                return rep is not None and rep["stale"] is True

            assert wait_for(victim_aged_out, timeout=10), (
                "survivors never aged the killed replica out of the"
                " fleet view: "
                + repr(survivors[0].fleet.snapshot().get("replicas"))
            )

            self._assert_failover_stitches(victim)
        finally:
            for r in replicas:
                r.kill()
                r.stop()
            tracing.configure(10_000)  # restore the default ring

    def _assert_failover_stitches(self, victim):
        """ISSUE 12 acceptance: the failover renders as ONE trace.
        Partition the shared ring into per-replica-pid trace files (the
        in-proc stand-in for each process's TPUC_TRACE_FILE), run the
        trace-merge pass, and assert some intent nonce has a pre-crash
        span under the victim's pid AND a post-crash adopt span under a
        survivor's pid, joined by a stitched flow across the two pids."""
        victim_pid = tracing.replica_pid(victim.ident)
        by_pid = {}
        for e in tracing.snapshot():
            by_pid.setdefault(e.get("pid"), []).append(e)
        assert victim_pid in by_pid, "victim recorded no trace events"
        docs = [
            {"traceEvents": evs, "displayTimeUnit": "ms",
             "metadata": {"epoch_us": 0.0}}
            for _pid, evs in sorted(by_pid.items())
        ]
        merged = tracing.merge_chrome(docs)
        merged_path = os.environ.get("TPUC_MERGED_TRACE_FILE")
        if merged_path:  # CI failure artifact (written on success too)
            with open(merged_path, "w") as f:
                json.dump(merged, f)

        spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        by_trace = {}
        for e in spans:
            trace_id = (e.get("args") or {}).get("trace_id")
            if trace_id:
                by_trace.setdefault(trace_id, []).append(e)
        stitched = [
            e for e in merged["traceEvents"]
            if e.get("ph") in ("s", "f") and e["args"].get("stitched")
        ]
        connected = []
        for trace_id, evs in by_trace.items():
            pids = {e["pid"] for e in evs}
            if victim_pid not in pids or len(pids) < 2:
                continue
            if not any(
                e["name"] == "adopt" and e["pid"] != victim_pid
                for e in evs
            ):
                continue
            if any(
                f["args"]["trace_id"] == trace_id for f in stitched
            ):
                connected.append(trace_id)
        summary = sorted(
            (t, sorted({e["pid"] for e in evs}))
            for t, evs in by_trace.items()
        )[:10]
        assert connected, (
            "no intent nonce rendered as one connected flow across the"
            " victim's and a survivor's pids after the merge — traces:"
            f" {summary}"
        )

    def test_rebalance_handoff_mid_wave(self):
        """A replica joining mid-wave is HANDED shards: the incumbent
        sheds (fence + lease release), the newcomer adopts scoped — the
        wave must converge with zero double-attach, exactly like
        failover but through the voluntary path."""
        store = _world(nodes=4)
        pool = RecordingPool(async_steps=2)
        mutations = []
        reports = []
        a = ShardedReplica(store, pool, "incumbent", 4, mutations, reports)
        try:
            a.start()
            assert wait_for(lambda: a.owned() == {0, 1, 2, 3},
                            timeout=2 * LEASE_S)
            _submit_wave(store, size=16)
            assert wait_for(
                lambda: any(res.status.pending_op is not None
                            for res in store.list(ComposableResource)),
                timeout=15,
            ), "kill missed the wave"
            b = ShardedReplica(store, pool, "newcomer", 4, mutations, reports)
            try:
                b.start()
                assert wait_for(
                    lambda: len(b.owned()) >= 1
                    and len(a.owned()) + len(b.owned()) == 4
                    and not (a.owned() & b.owned()),
                    timeout=6 * LEASE_S,
                ), f"rebalance never handed shards over: a={a.owned()} b={b.owned()}"
                handed = [
                    (ident, shard)
                    for ident, wins, _ in reports
                    if ident == "newcomer"
                    for shard, reason in wins.items()
                    if reason in ("handoff", "failover")
                ]
                assert handed, "newcomer never ran a scoped adoption pass"
                assert wait_for(
                    lambda: _running(store, "wave", 16), timeout=60,
                ), "wave never converged Ready after rebalance handoff"
                _assert_clean_accounting(store, pool, attached=16)
            finally:
                b.kill()
                b.stop()
        finally:
            a.kill()
            a.stop()
