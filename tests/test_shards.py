"""Shard-lease layer (runtime/shards.py): K shard leases over N replicas.

Tier-1 deterministic coverage — the electors are driven by direct
``tick()`` calls (no renew threads) wherever timing would otherwise make a
test flaky. The kill -9 chaos soak lives in tests/test_shard_failover.py
(markers slow+shard, ``make shard-soak``).
"""

from __future__ import annotations

import threading
import time
import zlib

import pytest

from tpu_composer.api import ComposableResource, Node, ObjectMeta
from tpu_composer.api.lease import Lease
from tpu_composer.api.meta import now_iso
from tpu_composer.api.types import PendingOp, RESOURCE_STATE_ATTACHING
from tpu_composer.controllers.adoption import adopt_pending_ops
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.fabric.dispatcher import FabricDispatcher
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.metrics import shard_handoffs_total
from tpu_composer.runtime.shards import (
    ShardFencedError,
    ShardLeaseElector,
    ShardOwnership,
    shard_for,
)
from tpu_composer.runtime.store import Store, StoreError


def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def elector(store, ident, k=4, lease=1.0, renew=0.2, **kw):
    return ShardLeaseElector(
        store, num_shards=k, identity=ident,
        lease_duration_s=lease, renew_period_s=renew, **kw,
    )


class TestShardFor:
    def test_stable_crc32_mapping(self):
        # The mapping IS the contract: two replicas (or two incarnations)
        # disagreeing on a key's shard is a double-attach. Pin it to crc32
        # so a refactor silently changing the hash fails here.
        for name in ("wave-a", "wave-a-0", "detach-tpu-0", ""):
            for k in (1, 2, 4, 7):
                assert shard_for(name, k) == (
                    0 if k <= 1 else zlib.crc32(name.encode()) % k
                )

    def test_every_shard_reachable(self):
        hit = {shard_for(f"res-{i}", 4) for i in range(64)}
        assert hit == {0, 1, 2, 3}

    def test_ownership_view(self):
        own = ShardOwnership(4)
        assert not own.owns_key("x")
        own._add(shard_for("x", 4))
        assert own.owns_key("x")
        assert own.owns_shard(shard_for("x", 4))
        own._discard(shard_for("x", 4))
        assert not own.owns_key("x")


class TestShardElector:
    def test_single_replica_owns_every_shard(self, store):
        a = elector(store, "replica-a")
        a.tick()
        assert a.owned_shards() == {0, 1, 2, 3}
        for i in range(4):
            lease = store.get(Lease, a.shard_lease_name(i))
            assert lease.spec.holder_identity == "replica-a"

    def test_two_replicas_balance_within_spread_one(self, store):
        a = elector(store, "replica-a")
        b = elector(store, "replica-b")
        for _ in range(6):
            a.tick()
            b.tick()
        owned_a, owned_b = a.owned_shards(), b.owned_shards()
        assert owned_a | owned_b == {0, 1, 2, 3}
        assert not owned_a & owned_b, "two owners for one shard"
        assert abs(len(owned_a) - len(owned_b)) <= 1

    def test_returning_replica_is_handed_shards(self, store):
        # a holds everything; b joins — the rebalancer sheds until the
        # spread is within 1, and every shed shard is picked up by b.
        a = elector(store, "replica-a")
        a.tick()
        assert len(a.owned_shards()) == 4
        b = elector(store, "replica-b")
        for _ in range(8):
            b.tick()
            a.tick()
        assert len(a.owned_shards()) == 2
        assert len(b.owned_shards()) == 2
        assert a.owned_shards() | b.owned_shards() == {0, 1, 2, 3}

    def test_dead_replica_shards_stolen_within_lease_duration(self, store):
        lease_s = 0.6
        a = elector(store, "replica-a", lease=lease_s, renew=0.1)
        b = elector(store, "replica-b", lease=lease_s, renew=0.1)
        a.tick()
        b.tick()
        a.tick()
        b.tick()
        assert b.owned_shards(), "b never balanced in"
        orphaned = a.owned_shards()
        assert orphaned
        # a dies (no release, renewals just stop). b keeps ticking: its
        # observation clock must watch a's renew_time sit unchanged for a
        # full lease duration before stealing.
        t_dead = time.monotonic()
        acquired_at = None
        deadline = time.monotonic() + 5 * lease_s
        while time.monotonic() < deadline:
            b.tick()
            if orphaned <= b.owned_shards():
                acquired_at = time.monotonic()
                break
            time.sleep(0.05)
        assert acquired_at is not None, "survivor never took the dead shards"
        took = acquired_at - t_dead
        assert took >= lease_s * 0.8, (
            f"stole a live-looking lease after only {took:.2f}s"
        )
        assert took <= 2 * lease_s + 0.5, (
            f"takeover took {took:.2f}s — more than ~one lease duration"
        )
        assert b.owned_shards() == {0, 1, 2, 3}
        assert shard_handoffs_total.value(reason="failover") >= 1

    def test_partitioned_replica_fences_before_successor_steals(self, store):
        """The shard twin of the single-leader fencing contract: a replica
        whose renewals fail must drop ownership (monotonic renew-deadline)
        strictly before its leases become stealable."""
        partitioned = threading.Event()

        class Partition:
            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def list(self, cls, label_selector=None):
                if partitioned.is_set() and cls is Lease:
                    raise StoreError("injected partition")
                return self._inner.list(cls, label_selector)

            def update(self, obj):
                if partitioned.is_set() and isinstance(obj, Lease):
                    raise StoreError("injected partition")
                return self._inner.update(obj)

            def create(self, obj):
                if partitioned.is_set() and isinstance(obj, Lease):
                    raise StoreError("injected partition")
                return self._inner.create(obj)

        lost = []
        a = elector(Partition(store), "replica-a", lease=1.2, renew=0.1,
                    renew_deadline_s=0.4)
        a.on_lose.append(lambda s, reason: lost.append((s, reason)))
        b = elector(store, "replica-b", lease=1.2, renew=0.1)
        a.tick()
        assert len(a.owned_shards()) == 4
        b.tick()  # b observes a's fresh leases
        t0 = time.monotonic()
        partitioned.set()
        # Drive a on its failure cadence until it fences everything.
        while a.owned_shards() and time.monotonic() - t0 < 3.0:
            a.tick()
            time.sleep(0.05)
        fenced_after = time.monotonic() - t0
        assert not a.owned_shards(), "partitioned replica never fenced"
        assert fenced_after < 1.2, (
            f"fenced {fenced_after:.2f}s after partition — leases were"
            " already stealable"
        )
        assert {reason for _, reason in lost} == {"fenced"}
        # b must NOT be able to steal yet: a's last renew_time is at most
        # renew_deadline + slack old, still inside the lease duration.
        b.tick()
        assert len(b.owned_shards()) == 0, (
            "successor stole before the lease expired — no fencing margin"
        )

        def b_took_everything():
            b.tick()
            return b.owned_shards() == {0, 1, 2, 3}

        # ...and once the leases genuinely expire, failover proceeds.
        assert wait_for(b_took_everything, timeout=6, interval=0.05), (
            "failover never happened after expiry"
        )

    def test_release_hands_off_instantly(self, store):
        a = elector(store, "replica-a")
        a.tick()
        a.release()
        for i in range(4):
            lease = store.get(Lease, a.shard_lease_name(i))
            assert lease.spec.holder_identity == ""
        b = elector(store, "replica-b")
        b.tick()
        assert b.owned_shards() == {0, 1, 2, 3}, (
            "released leases should be acquirable immediately (no expiry wait)"
        )

    def test_hooks_fire_batched_in_handoff_order(self, store):
        """A multi-shard win fires ONE on_acquire with every shard won
        that tick (so a K-shard bootstrap runs one scoped adoption pass,
        not K), ownership must already be ON when it runs (the adoption
        pass re-drives ops through this replica's dispatcher, whose
        owns-gate would silently discard them otherwise), and on_ready
        (the serving resync) fires strictly after."""
        events = []
        a = elector(store, "replica-a", k=2)
        a.on_acquire.append(lambda wins: events.append((
            "acquire", dict(wins),
            {s: a.ownership.owns_shard(s) for s in wins},
        )))
        a.on_ready.append(lambda shards: events.append((
            "ready", set(shards),
            {s: a.ownership.owns_shard(s) for s in shards},
        )))
        a.tick()
        assert [kind for kind, *_ in events] == ["acquire", "ready"], events
        kind, wins, owned_at_call = events[0]
        assert set(wins) == {0, 1}, "bootstrap win not batched into one call"
        assert set(wins.values()) == {"bootstrap"}
        assert all(owned_at_call.values()), (
            "shards not yet owned when on_acquire ran — dispatcher"
            " owns-gate would drop adoption's submissions"
        )
        assert events[1][1] == {0, 1}

    def test_adoption_repoll_passes_dispatcher_gate_on_handoff(self, store):
        """Regression: the scoped adoption pass fired by a shard win
        submits in-flight ops to THIS replica's dispatcher — the owns-gate
        keyed on the same ownership must accept them (ownership flips
        before on_acquire), or every handoff would silently drop its
        re-driven work until a poll timer."""
        from tests.test_crash_restart import RecordingPool

        store.create(Node(metadata=ObjectMeta(name="worker-0")))
        pool = RecordingPool(async_steps=2)  # forces the repoll path
        K = 2
        res = ComposableResource(metadata=ObjectMeta(name="handoff-res"))
        res.spec.type = "tpu"
        res.spec.model = "tpu-v4"
        res.spec.target_node = "worker-0"
        res.spec.chip_count = 1
        res.status.state = RESOURCE_STATE_ATTACHING
        store.create(res)
        got = store.get(ComposableResource, "handoff-res")
        got.status.state = RESOURCE_STATE_ATTACHING
        got.status.pending_op = PendingOp(
            verb="add", nonce="nonce-h", node="worker-0",
            started_at=now_iso(),
        )
        store.update_status(got)
        # The previous owner issued the attach; the fabric holds it async.
        try:
            pool.add_resource(got)
        except Exception:
            pass  # WaitingDeviceAttaching — exactly the repoll case
        b = elector(store, "replica-b", k=K)
        disp = FabricDispatcher(pool, batch_window=0.01, poll_interval=0.02,
                                owns=b.ownership.owns_key)
        outcomes = []
        b.on_acquire.append(lambda wins: outcomes.append(
            adopt_pending_ops(store, pool, disp, shards=set(wins),
                              num_shards=K)))
        b.tick()
        repolled = [n for rep in outcomes for n in rep.repolled]
        assert "handoff-res" in repolled
        # The dispatcher must actually be driving it (not silently fenced).
        assert wait_for(
            lambda: disp.op_state("add", "handoff-res") in ("pending", "done"),
            timeout=5,
        ), "owns-gate discarded the adoption's re-driven op"
        disp.kill()

    def test_startup_damping_caps_initial_grab(self, store):
        a = elector(store, "replica-a", k=4, lease=5.0,
                    expected_replicas=2)
        a.tick()
        assert len(a.owned_shards()) == 2, (
            "expected_replicas=2 should cap the first grab at ceil(4/2)"
        )

    def test_dead_member_heartbeats_are_garbage_collected(self, store):
        """Every kill -9'd incarnation leaves a member.<identity> Lease
        (identity embeds a per-boot uuid) — the tick must retire observed-
        dead heartbeats or the listing that gates every renewal grows
        forever with pod churn."""
        lease_s = 0.4
        dead = elector(store, "replica-dead", lease=lease_s, renew=0.1)
        dead.tick()  # creates its member lease + grabs shards
        survivor = elector(store, "replica-live", lease=lease_s, renew=0.1)
        survivor.tick()
        dead_name = dead._member_name
        assert store.try_get(Lease, dead_name) is not None
        # dead stops ticking (kill -9). The survivor must GC the heartbeat
        # after ~2x lease duration of observed death.
        def gc_done():
            survivor.tick()
            return (
                store.try_get(Lease, dead_name) is None
                and dead_name not in survivor._obs
            )
        assert wait_for(gc_done, timeout=10 * lease_s, interval=0.05), (
            "dead member heartbeat never garbage-collected"
        )
        # ...and the survivor's own heartbeat is untouched.
        assert store.try_get(Lease, survivor._member_name) is not None

    def test_acquire_returns_even_with_zero_shards(self, store):
        # K=1 with two replicas: the loser parks as a hot standby — its
        # Manager must still come up (healthz, controllers idle).
        a = elector(store, "replica-a", k=1)
        b = elector(store, "replica-b", k=1)
        a.tick()
        assert a.owned_shards() == {0}
        assert b.acquire(poll_interval=0.05) is True
        try:
            assert b.owned_shards() == set()
            assert b.is_leader  # never deposes — standby stays up
        finally:
            b.release()
            a.release()


class TestOwnershipEnforcement:
    def _world(self, store):
        n = Node(metadata=ObjectMeta(name="worker-0"))
        n.status.tpu_slots = 4
        store.create(n)
        return InMemoryPool()

    def _mid_attach_cr(self, store, name):
        res = ComposableResource(metadata=ObjectMeta(name=name))
        res.spec.type = "tpu"
        res.spec.model = "tpu-v4"
        res.spec.target_node = "worker-0"
        res.spec.chip_count = 1
        res.status.state = RESOURCE_STATE_ATTACHING
        store.create(res)
        got = store.get(ComposableResource, name)
        got.status.state = RESOURCE_STATE_ATTACHING
        got.status.pending_op = PendingOp(
            verb="add", nonce=f"nonce-{name}", node="worker-0",
            started_at=now_iso(),
        )
        return store.update_status(got)

    def test_fabric_write_path_fenced_for_unowned_key(self, store):
        pool = self._world(store)
        own = ShardOwnership(4)  # owns nothing
        rec = ComposableResourceReconciler(
            store, pool, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(), ownership=own,
        )
        res = self._mid_attach_cr(store, "fenced-res")
        with pytest.raises(ShardFencedError):
            rec._fabric_add(res)
        with pytest.raises(ShardFencedError):
            rec._fabric_remove(res)
        assert pool.get_resources() == [], "fenced mutation reached the fabric"
        # ShardFencedError is a quiet exception: requeue, no traceback spam.
        assert ShardFencedError in rec.quiet_exceptions

    def test_worker_drops_unowned_keys_without_reconciling(self, store):
        pool = self._world(store)
        own = ShardOwnership(4)
        reconciled = []
        rec = ComposableResourceReconciler(
            store, pool, FakeNodeAgent(pool=pool),
            timing=ResourceTiming(), ownership=own,
        )
        real = rec.reconcile
        rec.reconcile = lambda name: (reconciled.append(name), real(name))[1]
        self._mid_attach_cr(store, "owned-res")
        self._mid_attach_cr(store, "ghost-res")
        own._add(shard_for("owned-res", 4))
        assert shard_for("ghost-res", 4) != shard_for("owned-res", 4), (
            "test keys collapsed onto one shard — pick different names"
        )
        rec.start(workers=1)
        try:
            assert wait_for(lambda: "owned-res" in reconciled, timeout=5)
            time.sleep(0.3)
            assert "ghost-res" not in reconciled, (
                "worker reconciled a key outside the owned shards"
            )
        finally:
            rec.stop()

    def test_dispatcher_abandons_unowned_lanes_on_fence(self, store):
        pool = self._world(store)
        owned = {"keep-res"}
        disp = FabricDispatcher(
            pool, batch_window=30.0,  # park submissions in the lane FIFO
            owns=lambda name: name in owned,
        )
        keep = self._mid_attach_cr(store, "keep-res")
        lose = self._mid_attach_cr(store, "lose-res")
        owned.add("lose-res")
        from tpu_composer.fabric.provider import DispatchedAttaching

        for res in (keep, lose):
            with pytest.raises(DispatchedAttaching):
                disp.add_resource(res)
        assert disp.op_state("add", "keep-res") == "queued"
        assert disp.op_state("add", "lose-res") == "queued"
        # Shard lost: the fence must purge lose-res without firing latches.
        owned.discard("lose-res")
        assert disp.abandon_unowned() == 1
        assert disp.op_state("add", "lose-res") is None
        assert disp.op_state("add", "keep-res") == "queued"
        disp.kill()
        assert pool.get_resources() == []

    def test_dispatcher_refuses_unowned_op_at_execute_time(self, store):
        pool = self._world(store)
        owned = {"race-res"}
        disp = FabricDispatcher(
            pool, batch_window=0.01, poll_interval=0.02,
            owns=lambda name: name in owned,
        )
        res = self._mid_attach_cr(store, "race-res")
        # Lose ownership after submission but (deterministically) before
        # the batch window elapses — the execute-side check must drop it.
        from tpu_composer.fabric.provider import DispatchedAttaching

        with pytest.raises(DispatchedAttaching):
            disp.add_resource(res)
        owned.discard("race-res")
        assert wait_for(
            lambda: disp.op_state("add", "race-res") is None, timeout=5
        ), "fenced op never dropped"
        time.sleep(0.1)
        assert pool.get_resources() == [], (
            "fenced op reached the provider after ownership loss"
        )
        disp.kill()


class TestScopedAdoption:
    def test_adoption_scoped_to_shard_keys(self, store):
        store.create(Node(metadata=ObjectMeta(name="worker-0")))
        pool = InMemoryPool()
        K = 4
        names = [f"mig-{i}" for i in range(8)]
        by_shard = {}
        for name in names:
            res = ComposableResource(metadata=ObjectMeta(name=name))
            res.spec.type = "tpu"
            res.spec.model = "tpu-v4"
            res.spec.target_node = "worker-0"
            res.spec.chip_count = 1
            res.status.state = RESOURCE_STATE_ATTACHING
            store.create(res)
            got = store.get(ComposableResource, name)
            got.status.state = RESOURCE_STATE_ATTACHING
            got.status.pending_op = PendingOp(
                verb="add", nonce=f"n-{name}", node="worker-0",
                started_at=now_iso(),
            )
            store.update_status(got)
            by_shard.setdefault(shard_for(name, K), []).append(name)
        shard = next(s for s, members in by_shard.items() if members)
        report = adopt_pending_ops(
            store, pool, None, shards={shard}, num_shards=K
        )
        touched = set(
            report.adopted + report.reissued + report.repolled
            + report.cleared + report.deferred
        )
        assert touched == set(by_shard[shard]), (
            f"scoped pass touched {touched}, expected {set(by_shard[shard])}"
        )
        # Out-of-scope intents must be untouched — they belong to other
        # shards' owners.
        for name in names:
            res = store.get(ComposableResource, name)
            if name in touched:
                continue
            assert res.status.pending_op is not None, (
                f"{name} outside the scoped shard lost its intent"
            )

    def test_shard_migration_mid_attach_no_double_attach(self, store):
        """Satellite: intent written by replica A, shard stolen by B —
        B's scoped adoption must converge the op with zero double-attach
        and bit-identical budget/quarantine accounting."""
        from tests.test_crash_restart import (
            RecordingPool,
            assert_no_double_attach,
        )

        store.create(Node(metadata=ObjectMeta(name="worker-0")))
        pool = RecordingPool()
        name = "mid-attach"
        K = 2
        res = ComposableResource(metadata=ObjectMeta(name=name))
        res.spec.type = "tpu"
        res.spec.model = "tpu-v4"
        res.spec.target_node = "worker-0"
        res.spec.chip_count = 2
        res.status.state = RESOURCE_STATE_ATTACHING
        store.create(res)
        got = store.get(ComposableResource, name)
        got.status.state = RESOURCE_STATE_ATTACHING
        got.status.pending_op = PendingOp(
            verb="add", nonce="nonce-mid", node="worker-0",
            started_at=now_iso(),
        )
        got = store.update_status(got)
        # Replica A issued the attach (it materialized at the fabric) but
        # crashed/was fenced before recording the outcome.
        pool.add_resource(got)
        before_free = pool.free_chips("tpu-v4")
        # Replica B steals the shard: its on_acquire hook runs the scoped
        # adoption pass over exactly this key.
        report = adopt_pending_ops(
            store, pool, None,
            shards={shard_for(name, K)}, num_shards=K,
        )
        assert name in report.adopted
        after = store.get(ComposableResource, name)
        assert after.status.pending_op is None
        assert len(after.status.device_ids) == 2
        assert after.status.attach_attempts == 0, "adoption rewrote the budget"
        assert not after.status.quarantined
        assert pool.free_chips("tpu-v4") == before_free, (
            "adoption re-attached chips the fabric already held"
        )
        assert_no_double_attach(pool.events)
