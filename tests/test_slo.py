"""SLO engine: quantile math, multi-window burn rates, and the brownout
acceptance spine for ISSUE 11.

Tier-1 acceptance: a ChaosFabricProvider brownout stalling the attach path
trips the attach-to-ready SLO burn alert — SloBreached Event emitted and
``tpuc_slo_breached{slo="attach_p99"}`` set — and the alert clears after
recovery; and the SLO fires while the repair breaker is still closed (the
alert is the EARLY signal, the breaker the containment backstop).
"""

from __future__ import annotations

import time

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import REQUEST_STATE_RUNNING
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
    RepairConfig,
    RequestTiming,
)
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.fabric.chaos import ChaosFabricProvider
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.fabric.provider import FabricError
from tpu_composer.runtime.events import EventRecorder
from tpu_composer.runtime.metrics import (
    Histogram,
    attach_to_ready_seconds,
    repair_breaker_open,
    slo_breached,
    slo_burn_rate,
)
from tpu_composer.runtime.slo import Objective, SloEngine, default_objectives
from tpu_composer.runtime.store import Store

MODEL = "tpu-v4"


# ---------------------------------------------------------------------------
# Histogram quantiles — the SLO engine's substrate
# ---------------------------------------------------------------------------

class TestHistogramPercentile:
    def test_empty_series_returns_none_not_a_boundary(self):
        h = Histogram("t_slo_empty")
        assert h.percentile(0.5) is None
        assert h.percentile(0.99, op="x") is None

    def test_exact_path_while_samples_complete(self):
        h = Histogram("t_slo_exact")
        for v in (0.1, 0.2, 0.3, 0.4, 0.5):
            h.observe(v)
        assert h.percentile(0.5) == 0.3
        assert h.percentile(1.0) == 0.5

    def test_bucket_interpolation_after_sample_eviction(self):
        # Force the bounded sample ring to evict so percentile must fall
        # back to bucket counts — the answer must interpolate INSIDE the
        # target bucket, not return its upper bound.
        h = Histogram("t_slo_interp", buckets=(0.1, 0.2, 0.4, 0.8))
        h._max_samples = 4
        h._samples.clear()
        for _ in range(100):
            h.observe(0.15)  # all land in the (0.1, 0.2] bucket
        p50 = h.percentile(0.5)
        assert p50 is not None
        assert 0.1 < p50 < 0.2, p50  # interpolated, not the 0.2 boundary
        # Uniform mass across one bucket: p50 ~ midpoint.
        assert abs(p50 - 0.15) < 0.011, p50

    def test_count_le_interpolates_within_bucket(self):
        h = Histogram("t_slo_countle", buckets=(0.1, 0.2, 0.4))
        for _ in range(10):
            h.observe(0.15)
        for _ in range(10):
            h.observe(0.3)
        assert h.total_count() == 20
        # 0.2 covers the whole first occupied bucket.
        assert h.total_count_le(0.2) == 10
        # 0.3 is halfway through (0.2, 0.4]: 10 + 10*0.5.
        assert abs(h.total_count_le(0.3) - 15.0) < 1e-9
        # Overflow-bucket observations never count as <= a finite value.
        h.observe(99.0)
        assert h.total_count_le(0.4) == 20


# ---------------------------------------------------------------------------
# Burn-rate math (driven with an injected clock)
# ---------------------------------------------------------------------------

def _engine(h, threshold=0.1, target=0.9, **kw):
    kw.setdefault("fast_window", 30.0)
    kw.setdefault("slow_window", 300.0)
    kw.setdefault("burn_threshold", 2.0)
    return SloEngine(
        objectives=[Objective("obj", h, threshold, target)], **kw
    )


class TestBurnRate:
    def test_no_traffic_means_zero_burn(self):
        h = Histogram("t_burn_idle")
        eng = _engine(h)
        eng.evaluate(now=0.0)
        eng.evaluate(now=10.0)
        assert eng.burn_rates("obj") == (0.0, 0.0)
        assert not eng.breached("obj")

    def test_fast_window_trips_before_slow(self):
        h = Histogram("t_burn_fastfirst")
        eng = _engine(h)
        # A long good history fills the slow window...
        for t in range(0, 280, 10):
            for _ in range(10):
                h.observe(0.01)
            eng.evaluate(now=float(t))
        # ...then a burst of bad: the fast window (only bad inside it)
        # saturates while the slow window is still diluted by history.
        for _ in range(20):
            h.observe(1.0)
        eng.evaluate(now=290.0)
        fast, slow = eng.burn_rates("obj")
        assert fast >= eng.burn_threshold, (fast, slow)
        assert slow < eng.burn_threshold, (fast, slow)
        # Multi-window AND: not breached yet — a blip must not page.
        assert not eng.breached("obj")
        assert slo_breached.value(slo="obj") == 0.0
        # Sustained badness saturates the slow window too -> breach.
        t = 290.0
        while not eng.breached("obj") and t < 600.0:
            t += 10.0
            for _ in range(20):
                h.observe(1.0)
            eng.evaluate(now=t)
        assert eng.breached("obj"), eng.burn_rates("obj")
        assert slo_breached.value(slo="obj") == 1.0
        assert slo_burn_rate.value(slo="obj", window="fast") >= 2.0

    def test_recovery_clears_via_the_fast_window(self):
        h = Histogram("t_burn_recover")
        recorder = EventRecorder()
        eng = _engine(h, recorder=recorder)
        eng.evaluate(now=0.0)
        for _ in range(50):
            h.observe(1.0)
        eng.evaluate(now=10.0)
        assert eng.breached("obj")
        breach_evs = [e for e in recorder.all() if e.reason == "SloBreached"]
        assert len(breach_evs) == 1 and e_kind(breach_evs[0]) == "SLO"
        # Good traffic + the bad burst aging out of the fast window.
        for t in (20.0, 30.0, 41.0, 50.0):
            for _ in range(30):
                h.observe(0.01)
            eng.evaluate(now=t)
        assert not eng.breached("obj"), eng.burn_rates("obj")
        assert slo_breached.value(slo="obj") == 0.0
        assert any(e.reason == "SloRecovered" for e in recorder.all())

    def test_defaults_cover_the_four_objectives(self):
        names = {o.name for o in default_objectives()}
        assert names == {
            "attach_p99", "completion_p50", "queue_wait_p99", "repair_p99"
        }
        # Per-objective off switch: a <=0 threshold drops it.
        assert {o.name for o in default_objectives(queue_p99_s=0)} == {
            "attach_p99", "completion_p50", "repair_p99"
        }


def e_kind(ev):
    return ev.kind


# ---------------------------------------------------------------------------
# Brownout acceptance: chaos stalls attaches -> attach SLO burns -> clears
# ---------------------------------------------------------------------------

def make_world(nodes=4):
    store = Store()
    for i in range(nodes):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = InMemoryPool(chips={MODEL: 64})
    chaos = ChaosFabricProvider(pool)
    agent = FakeNodeAgent(pool=pool)
    req_rec = ComposabilityRequestReconciler(
        store, chaos,
        timing=RequestTiming(updating_poll=0.01, cleaning_poll=0.01,
                             running_poll=5.0, repair_poll=0.01),
        repair=RepairConfig(),
    )
    res_rec = ComposableResourceReconciler(
        store, chaos, agent,
        timing=ResourceTiming(health_failure_threshold=2,
                              health_recovery_threshold=1),
    )
    return store, pool, chaos, req_rec, res_rec


def pump(store, req_rec, res_rec, names, steps=80, done=None):
    for _ in range(steps):
        for name in names:
            try:
                req_rec.reconcile(name)
            except FabricError:
                pass
        for c in store.list(ComposableResource):
            try:
                res_rec.reconcile(c.metadata.name)
            except FabricError:
                pass
        if done is not None and done():
            return


def running(store, name):
    req = store.try_get(ComposabilityRequest, name)
    return req is not None and req.status.state == REQUEST_STATE_RUNNING


def attach_batch(store, req_rec, res_rec, names, size=4):
    for name in names:
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name=name),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="tpu", model=MODEL, size=size)),
        ))
    pump(store, req_rec, res_rec, names,
         done=lambda: all(running(store, n) for n in names))
    for name in names:
        assert running(store, name), name
        store.delete(ComposabilityRequest, name)
    pump(store, req_rec, res_rec, names, steps=120,
         done=lambda: all(
             store.try_get(ComposabilityRequest, n) is None for n in names
         ))


class TestBrownoutSlo:
    def test_brownout_trips_attach_slo_and_clears_on_recovery(self):
        store, pool, chaos, req_rec, res_rec = make_world()
        recorder = req_rec.recorder
        eng = SloEngine(
            objectives=[Objective(
                "attach_p99", attach_to_ready_seconds, 0.1, 0.90,
                "attach-to-ready under brownout",
            )],
            recorder=recorder,
            fast_window=30.0, slow_window=120.0, burn_threshold=2.0,
        )
        # Healthy baseline: fast attaches, well under the 150 ms objective.
        eng.evaluate(now=0.0)
        attach_batch(store, req_rec, res_rec, ["ok-1", "ok-2"])
        eng.evaluate(now=10.0)
        assert not eng.breached("attach_p99")

        # Brownout: the fabric endpoint goes dark mid-attach. The requests
        # stall (every provider call raises) until the brownout lifts, so
        # their eventual attach-to-ready latency carries the outage.
        chaos.blackout()
        for name in ("slow-1", "slow-2"):
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name=name),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model=MODEL, size=4)),
            ))
        pump(store, req_rec, res_rec, ["slow-1", "slow-2"], steps=5,
             done=lambda: False)
        time.sleep(0.2)  # the outage is what the latency histogram records
        chaos.heal()
        pump(store, req_rec, res_rec, ["slow-1", "slow-2"],
             done=lambda: running(store, "slow-1") and running(store, "slow-2"))
        eng.evaluate(now=20.0)
        assert eng.breached("attach_p99"), eng.burn_rates("attach_p99")
        assert slo_breached.value(slo="attach_p99") == 1.0
        evs = [e for e in recorder.all() if e.reason == "SloBreached"]
        assert evs and evs[0].kind == "SLO" and evs[0].name == "attach_p99"

        # Recovery: healthy attaches while the bad burst ages out of the
        # fast window -> the alert clears and says so.
        for n2 in ("slow-1", "slow-2"):
            store.delete(ComposabilityRequest, n2)
        pump(store, req_rec, res_rec, ["slow-1", "slow-2"], steps=120,
             done=lambda: all(
                 store.try_get(ComposabilityRequest, n) is None
                 for n in ("slow-1", "slow-2")))
        attach_batch(store, req_rec, res_rec, ["ok-3", "ok-4"])
        eng.evaluate(now=60.0)  # past the fast window's reach of the burst
        assert not eng.breached("attach_p99"), eng.burn_rates("attach_p99")
        assert slo_breached.value(slo="attach_p99") == 0.0
        assert any(e.reason == "SloRecovered" for e in recorder.all())

    def test_brownout_slo_fires_before_repair_breaker_opens(self):
        # The ordering that makes the SLO the EARLY warning: one node's
        # brownout slows attaches enough to burn the attach objective
        # while the degraded fraction is still below the repair breaker's
        # threshold (breaker needs >50% of >=4 attached members bad).
        store, pool, chaos, req_rec, res_rec = make_world(nodes=8)
        eng = SloEngine(
            objectives=[Objective(
                "attach_p99", attach_to_ready_seconds, 0.1, 0.90,
            )],
            fast_window=30.0, slow_window=120.0, burn_threshold=2.0,
        )
        repair_breaker_open.set(0.0)
        # An established healthy request keeps the breaker's denominator
        # populated (4 Online members).
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="steady"),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="tpu", model=MODEL, size=16)),
        ))
        pump(store, req_rec, res_rec, ["steady"],
             done=lambda: running(store, "steady"))
        assert running(store, "steady")
        eng.evaluate(now=0.0)

        # Brownout stalls NEW attaches (scoped: the endpoint blacks out,
        # no post-Ready member death — the breaker has nothing to open
        # for). The attach SLO burns first.
        chaos.blackout()
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="late"),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="tpu", model=MODEL, size=4)),
        ))
        pump(store, req_rec, res_rec, ["late"], steps=5, done=lambda: False)
        time.sleep(0.2)
        chaos.heal()
        pump(store, req_rec, res_rec, ["late", "steady"],
             done=lambda: running(store, "late"))
        eng.evaluate(now=10.0)
        assert eng.breached("attach_p99"), eng.burn_rates("attach_p99")
        # ...and at that moment the repair breaker never opened: the SLO
        # alert led, the containment backstop stayed closed.
        assert repair_breaker_open.value() == 0.0
