"""Teardown soak: the round-3 bench crasher as a permanent regression gate.

BENCH_r03 died with "bench-9 teardown never completed" — a NotFound race
on the deletion path under concurrent load (VERDICT r3 missing #1). This
test runs the same storm shape through the live threaded manager: four
concurrent lanes of create -> Running -> delete -> purged cycles, with
every fifth cycle adversarially yanking child finalizers mid-teardown to
force the purged-between-read-and-PUT interleaving. 200 cycles complete
in a few seconds; any wedged teardown fails the lane by timeout.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import LABEL_MANAGED_BY
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
    RequestTiming,
)
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.store import Store

LANES = 4
CYCLES_PER_LANE = 50


def test_200_cycle_teardown_storm_with_purge_races():
    store = Store()
    for i in range(8):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = InMemoryPool(chips={"tpu-v4": 64})
    agent = FakeNodeAgent(pool=pool)
    mgr = Manager(store, health_addr="127.0.0.1:0")
    mgr.add_controller(ComposabilityRequestReconciler(
        store, pool,
        timing=RequestTiming(updating_poll=0.05, cleaning_poll=0.02,
                             running_poll=5.0)))
    mgr.add_controller(ComposableResourceReconciler(
        store, pool, agent,
        timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.02,
                              detach_poll=0.05, detach_fast=0.02,
                              busy_poll=0.05)))
    # The adversarial purges orphan fabric attachments by design (a child
    # deleted without running detach) — reclaiming those is the
    # UpstreamSyncer's anti-drift job, so the soak runs the full system.
    mgr.add_runnable(UpstreamSyncer(store, pool, period=0.05, grace=0.1))
    mgr.start(workers_per_controller=2)

    fails: list = []

    def cycle(i: int) -> None:
        name = f"soak-{i}"  # body wrapped by the lane runner's except
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name=name),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="tpu", model="tpu-v4", size=4)),
        ))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            r = store.try_get(ComposabilityRequest, name)
            if r is not None and r.status.state == "Running":
                break
            time.sleep(0.01)
        else:
            fails.append(f"{name}: never Running")
            return
        store.delete(ComposabilityRequest, name)
        if i % 5 == 0:
            # Adversary: purge children out from under the teardown.
            time.sleep(0.01)
            for c in store.list(ComposableResource):
                if (c.metadata.labels.get(LABEL_MANAGED_BY) == name
                        and c.being_deleted):
                    c.metadata.finalizers = []
                    try:
                        store.update(c)
                    except Exception:  # noqa: BLE001 - racing the controller
                        pass
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if store.try_get(ComposabilityRequest, name) is None:
                return
            time.sleep(0.01)
        fails.append(f"{name}: teardown never completed")

    try:
        lanes = []
        for lane in range(LANES):
            def run(lane=lane):
                for j in range(CYCLES_PER_LANE):
                    i = lane * CYCLES_PER_LANE + j
                    try:
                        cycle(i)
                    except Exception as e:  # noqa: BLE001 - a dead lane must FAIL
                        fails.append(f"soak-{i}: lane crashed: {e!r}")
                        return

            t = threading.Thread(target=run)
            t.start()
            lanes.append(t)
        for t in lanes:
            t.join()
        # Settle: the syncer needs a few grace periods to reclaim
        # attachments orphaned by the adversarial purges.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if (pool.free_chips("tpu-v4") == 64
                    and not store.list(ComposableResource)):
                break
            time.sleep(0.05)
    finally:
        mgr.stop()

    assert not fails, fails[:10]
    assert pool.free_chips("tpu-v4") == 64  # every chip reclaimed
    leftovers = [k for k in store.keys()
                 if k[0] in ("ComposabilityRequest", "ComposableResource")]
    assert leftovers == [], leftovers[:10]


def test_wire_path_teardown_cycles():
    """The same storm through the KubeStore + fake-apiserver wire path —
    the exact stack BENCH_r03 crashed on (watch-cache staleness made the
    finalizer-removal PUT 404 loop). Fewer cycles than the in-proc storm:
    each cycle pays real HTTP round trips."""
    from tests.fake_apiserver import (
        FakeApiServer,
        core_node_doc,
        operator_resources,
    )

    from tpu_composer import GROUP, VERSION
    from tpu_composer.runtime.kubestore import (
        CHIP_RESOURCE,
        KubeConfig,
        KubeStore,
    )

    srv = FakeApiServer(operator_resources(GROUP, VERSION))
    srv.start()
    store = None
    mgr = None
    try:
        for i in range(4):
            srv.put_object(
                "/api/v1/nodes",
                core_node_doc(f"worker-{i}", chips=8,
                              chip_resource=CHIP_RESOURCE),
            )
        store = KubeStore(config=KubeConfig(host=srv.url),
                          watch_reconnect_s=0.05)
        pool = InMemoryPool(chips={"tpu-v4": 32})
        agent = FakeNodeAgent(pool=pool)
        mgr = Manager(store, health_addr="127.0.0.1:0")
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool,
            timing=RequestTiming(updating_poll=0.05, cleaning_poll=0.02,
                                 running_poll=5.0)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, agent,
            timing=ResourceTiming(attach_poll=0.05, visibility_poll=0.02,
                                  detach_poll=0.05, detach_fast=0.02,
                                  busy_poll=0.05)))
        mgr.start(workers_per_controller=2)

        fails: list = []

        def cycle(i: int) -> None:
            name = f"wire-{i}"
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name=name),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4)),
            ))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                r = store.try_get(ComposabilityRequest, name)
                if r is not None and r.status.state == "Running":
                    break
                time.sleep(0.01)
            else:
                fails.append(f"{name}: never Running")
                return
            store.delete(ComposabilityRequest, name)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if store.try_get(ComposabilityRequest, name) is None:
                    return
                time.sleep(0.01)
            fails.append(f"{name}: teardown never completed")

        lanes = []
        for lane in range(2):
            def run(lane=lane):
                for j in range(15):
                    i = lane * 15 + j
                    try:
                        cycle(i)
                    except Exception as e:  # noqa: BLE001 - lane must FAIL
                        fails.append(f"wire-{i}: lane crashed: {e!r}")
                        return

            t = threading.Thread(target=run)
            t.start()
            lanes.append(t)
        for t in lanes:
            t.join()
        assert not fails, fails[:10]
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if pool.free_chips("tpu-v4") == 32:
                break
            time.sleep(0.05)
        assert pool.free_chips("tpu-v4") == 32
    finally:
        if mgr is not None:
            mgr.stop()
        if store is not None:
            store.close()
        srv.stop()


@pytest.mark.skipif(
    os.environ.get("TPUC_CHAOS") != "1",
    reason="chaos storm is opt-in (TPUC_CHAOS=1): ~90s per seed",
)
def test_wire_chaos_storm():
    """Opt-in chaos: create/resize/delete lanes racing a node
    delete/recreate adversary over the wire path, with the syncer
    reclaiming orphans — and (r5) a wire adversary resetting every live
    watch socket and compacting the server's event history mid-flight, so
    the 410-Expired -> relist recovery runs with controllers mid-lifecycle,
    not just in the dedicated hostile-wire tests. Ran clean on 7 seeds
    when the r4 tombstone fix landed; kept runnable for race hunts."""
    import random

    from tests.fake_apiserver import (
        FakeApiServer,
        core_node_doc,
        operator_resources,
    )

    from tpu_composer import GROUP, VERSION
    from tpu_composer.api.types import Node
    from tpu_composer.runtime.kubestore import (
        CHIP_RESOURCE,
        KubeConfig,
        KubeStore,
    )
    from tpu_composer.runtime.store import ConflictError, NotFoundError

    seed = int(os.environ.get("TPUC_CHAOS_SEED", "1"))
    # Per-thread rngs: one shared Random across 4 threads would make the
    # seed knob non-reproducible (draw order depends on interleaving).
    lane_rngs = [random.Random(seed * 100 + i) for i in range(3)]
    chaos_rng = random.Random(seed * 100 + 99)
    srv = FakeApiServer(operator_resources(GROUP, VERSION))
    srv.start()
    node_prefix = "/api/v1/nodes"
    store = mgr = None
    stop = threading.Event()
    try:
        for i in range(6):
            srv.put_object(node_prefix, core_node_doc(
                f"worker-{i}", chips=8, chip_resource=CHIP_RESOURCE))
        store = KubeStore(config=KubeConfig(host=srv.url),
                          watch_reconnect_s=0.05)
        pool = InMemoryPool(chips={"tpu-v4": 48})
        agent = FakeNodeAgent(pool=pool)
        mgr = Manager(store, health_addr="127.0.0.1:0")
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool, timing=RequestTiming(
                updating_poll=0.05, cleaning_poll=0.02, running_poll=2.0)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, agent, timing=ResourceTiming(
                attach_poll=0.05, visibility_poll=0.02, detach_poll=0.05,
                detach_fast=0.02, busy_poll=0.05, health_poll=1.0)))
        mgr.add_runnable(UpstreamSyncer(store, pool, period=0.1, grace=0.3))
        mgr.start(workers_per_controller=2)

        fails: list = []

        def lane(lane_id: int) -> None:
            rng = lane_rngs[lane_id]
            for j in range(8):
                name = f"chaos-{lane_id}-{j}"
                size = rng.choice([4, 8])
                try:
                    store.create(ComposabilityRequest(
                        metadata=ObjectMeta(name=name),
                        spec=ComposabilityRequestSpec(
                            resource=ResourceDetails(
                                type="tpu", model="tpu-v4", size=size))))
                except Exception as e:  # noqa: BLE001
                    fails.append(f"{name}: create {e!r}")
                    continue
                deadline = time.monotonic() + 40
                while time.monotonic() < deadline:
                    r = store.try_get(ComposabilityRequest, name)
                    if r is not None and r.status.state == "Running":
                        break
                    time.sleep(0.02)
                else:
                    fails.append(f"{name}: never Running")
                    continue
                if rng.random() < 0.5:
                    for _ in range(10):
                        try:
                            r = store.get(ComposabilityRequest, name)
                            r.spec.resource.size = 8 if size == 4 else 4
                            store.update(r)
                            break
                        except (ConflictError, NotFoundError):
                            time.sleep(0.02)
                    deadline = time.monotonic() + 40
                    while time.monotonic() < deadline:
                        r = store.try_get(ComposabilityRequest, name)
                        if r is None or (
                            r.status.state == "Running"
                            and sum(len(rs.device_ids)
                                    for rs in r.status.resources.values())
                            == r.spec.resource.size
                        ):
                            break
                        time.sleep(0.02)
                    else:
                        fails.append(f"{name}: resize never settled")
                        continue
                try:
                    store.delete(ComposabilityRequest, name)
                except NotFoundError:
                    pass
                deadline = time.monotonic() + 40
                while time.monotonic() < deadline:
                    if store.try_get(ComposabilityRequest, name) is None:
                        break
                    time.sleep(0.02)
                else:
                    fails.append(f"{name}: teardown never completed")

        def node_chaos() -> None:
            rng = chaos_rng
            while not stop.is_set():
                time.sleep(rng.uniform(1.5, 3.0))
                nm = f"worker-{rng.randrange(6)}"
                try:
                    store.delete(Node, nm)
                except Exception:  # noqa: BLE001 - adversary, best effort
                    pass
                time.sleep(rng.uniform(0.3, 0.8))
                try:
                    srv.put_object(node_prefix, core_node_doc(
                        nm, chips=8, chip_resource=CHIP_RESOURCE))
                except Exception:  # noqa: BLE001
                    pass

        def wire_chaos() -> None:
            # The r5 hostile-wire personas under full load: reset every
            # live watch socket, and sometimes compact the event history so
            # the reconnect resumes from behind the horizon and must take
            # the 410 -> relist path with controllers mid-lifecycle.
            rng = random.Random(seed * 100 + 98)
            while not stop.is_set():
                time.sleep(rng.uniform(2.0, 4.0))
                if rng.random() < 0.4:
                    srv.compact()
                srv.kill_watch_connections()

        def lane_guard(i: int) -> None:
            try:
                lane(i)
            except Exception as e:  # noqa: BLE001 - a dead lane must FAIL
                fails.append(f"lane-{i} crashed: {e!r}")

        lanes = [threading.Thread(target=lane_guard, args=(i,))
                 for i in range(3)]
        nc = threading.Thread(target=node_chaos)
        wc = threading.Thread(target=wire_chaos)
        for t in lanes:
            t.start()
        nc.start()
        wc.start()
        for t in lanes:
            t.join()
        stop.set()
        nc.join()
        wc.join()
        assert not fails, fails[:8]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if (not store.list(ComposabilityRequest)
                    and not store.list(ComposableResource)
                    and pool.free_chips("tpu-v4") == 48):
                break
            time.sleep(0.1)
        assert pool.free_chips("tpu-v4") == 48
        assert store.list(ComposabilityRequest) == []
        assert store.list(ComposableResource) == []
    finally:
        stop.set()
        if mgr is not None:
            mgr.stop()
        if store is not None:
            store.close()
        srv.stop()
