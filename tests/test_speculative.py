"""Speculative decoding: greedy draft-and-verify must reproduce target
greedy decoding EXACTLY, for any draft — that is the correctness contract
that makes the speedup free."""

import jax
import jax.numpy as jnp
import pytest

from tpu_composer.models.decode import generate
from tpu_composer.models.quant import quantize_decode_params
from tpu_composer.models.speculative import speculative_generate
from tpu_composer.models.transformer import ModelConfig, init_params


def _cfg(**kw):
    base = dict(vocab_size=128, d_model=128, n_layers=2, n_heads=8,
                n_kv_heads=2, d_ff=192, max_seq=96, dtype=jnp.float32)
    base.update(kw)
    return ModelConfig(**base)


class TestSpeculativeExactness:
    @pytest.mark.parametrize("gamma", [1, 3, 4])
    def test_matches_target_greedy_with_weak_draft(self, gamma):
        """Draft = a DIFFERENT (smaller) model: acceptance is imperfect,
        output must still be byte-identical to target-only greedy."""
        c = _cfg()
        dc = _cfg(n_layers=1, d_ff=96)
        params = init_params(c, jax.random.key(0))
        draft = init_params(dc, jax.random.key(7))
        prompt = jax.random.randint(jax.random.key(1), (1, 6), 0, c.vocab_size)
        ref = generate(params, prompt, c, max_new_tokens=16, max_seq=96)
        spec = speculative_generate(
            params, draft, prompt, c, draft_config=dc,
            max_new_tokens=16, gamma=gamma, max_seq=96,
        )
        assert spec.tolist() == ref.tolist()

    def test_perfect_draft_accepts_everything(self):
        """Draft == target: every round accepts all gamma drafts, so the
        loop runs ~max_new/(gamma+1) verify rounds — and is still exact."""
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, c.vocab_size)
        ref = generate(params, prompt, c, max_new_tokens=12, max_seq=96)
        spec = speculative_generate(
            params, params, prompt, c, max_new_tokens=12, gamma=4, max_seq=96,
        )
        assert spec.tolist() == ref.tolist()

    def test_quantized_draft(self):
        """The natural free draft: the target's own int8-quantized weights.
        Exactness still holds — the draft only proposes."""
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        draft = quantize_decode_params(params)
        prompt = jax.random.randint(jax.random.key(1), (1, 5), 0, c.vocab_size)
        ref = generate(params, prompt, c, max_new_tokens=12, max_seq=96)
        spec = speculative_generate(
            params, draft, prompt, c, max_new_tokens=12, gamma=3, max_seq=96,
        )
        assert spec.tolist() == ref.tolist()

    def test_gqa_and_mqa_targets(self):
        c = _cfg(n_kv_heads=1)
        params = init_params(c, jax.random.key(2))
        draft = init_params(_cfg(n_kv_heads=1, n_layers=1), jax.random.key(3))
        prompt = jnp.array([[9, 4, 17]], jnp.int32)
        ref = generate(params, prompt, c, max_new_tokens=10, max_seq=96)
        spec = speculative_generate(
            params, draft, prompt, c,
            draft_config=_cfg(n_kv_heads=1, n_layers=1),
            max_new_tokens=10, gamma=2, max_seq=96,
        )
        assert spec.tolist() == ref.tolist()

    def test_rejects_batch_and_capacity_errors(self):
        c = _cfg()
        params = init_params(c, jax.random.key(0))
        two = jnp.zeros((2, 4), jnp.int32)
        with pytest.raises(ValueError):
            speculative_generate(params, params, two, c, max_new_tokens=4)
        long_prompt = jnp.zeros((1, 90), jnp.int32)
        with pytest.raises(ValueError):
            speculative_generate(params, params, long_prompt, c,
                                 max_new_tokens=16, gamma=4, max_seq=96)


class TestDecodeChunk:
    @pytest.mark.parametrize("kv_quant", [False, True])
    def test_paged_speculative_matches_dense_and_target(self, kv_quant):
        """paged_speculative_generate (block-pool caches for target AND
        draft) reproduces both the dense speculative run and target-only
        greedy — the same exactness contract, paged."""
        from tpu_composer.models.speculative import (
            paged_speculative_generate,
        )

        c = _cfg()
        dc = _cfg(n_layers=1, d_ff=96)
        params = init_params(c, jax.random.key(0))
        draft = init_params(dc, jax.random.key(7))
        prompt = jax.random.randint(jax.random.key(2), (1, 5), 0,
                                    c.vocab_size)
        ref = generate(params, prompt, c, max_new_tokens=12, max_seq=96,
                       kv_quant=kv_quant)
        dense = speculative_generate(
            params, draft, prompt, c, draft_config=dc,
            max_new_tokens=12, gamma=3, max_seq=96, kv_quant=kv_quant,
        )
        paged = paged_speculative_generate(
            params, draft, prompt, c, num_blocks=8, block_size=8,
            draft_config=dc, max_new_tokens=12, gamma=3,
            kv_quant=kv_quant,
        )
        assert paged.tolist() == dense.tolist() == ref.tolist()

    def test_paged_speculative_capacity_check(self):
        from tpu_composer.models.speculative import (
            paged_speculative_generate,
        )

        c = _cfg()
        params = init_params(c, jax.random.key(0))
        prompt = jnp.zeros((1, 6), jnp.int32)
        with pytest.raises(ValueError, match="blocks"):
            paged_speculative_generate(
                params, params, prompt, c, num_blocks=2, block_size=8,
                max_new_tokens=32, gamma=4,
            )

    def test_chunk_equals_stepwise(self):
        """decode_chunk(T) must equal T successive decode_steps — same
        logits, same cache contents (the verify step's correctness)."""
        from tpu_composer.models.decode import decode_chunk, decode_step, prefill

        c = _cfg()
        params = init_params(c, jax.random.key(0))
        prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, c.vocab_size)
        toks = jax.random.randint(jax.random.key(2), (2, 3), 0, c.vocab_size)

        _, cache_a = prefill(params, prompt, c, max_seq=32)
        chunk_logits, cache_a = decode_chunk(params, cache_a, toks, c)

        _, cache_b = prefill(params, prompt, c, max_seq=32)
        step_logits = []
        for i in range(3):
            lg, cache_b = decode_step(params, cache_b, toks[:, i], c)
            step_logits.append(lg)
        for i in range(3):
            assert float(jnp.abs(chunk_logits[:, i] - step_logits[i]).max()) < 2e-4
        assert int(cache_a.length[0]) == int(cache_b.length[0])
        assert float(jnp.abs(cache_a.k - cache_b.k).max()) < 1e-5

    def test_moe_target_is_exact(self):
        """MoE targets verify exactly: decode chunks route with drop-free
        capacity (T*top_k), so a chunk computes what T single steps would
        and the greedy-equivalence contract extends to the MoE family."""
        from tpu_composer.models.moe import MoEConfig
        from tpu_composer.models.moe import init_params as moe_init

        mc = MoEConfig(vocab_size=64, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=96, max_seq=96, dtype=jnp.float32,
                       n_experts=2, top_k=1, capacity_factor=2.0,
                       moe_period=2)
        mp = moe_init(mc, jax.random.key(0))
        dc = MoEConfig(vocab_size=64, d_model=64, n_layers=1, n_heads=4,
                       n_kv_heads=2, d_ff=96, max_seq=96, dtype=jnp.float32,
                       n_experts=2, top_k=1, capacity_factor=2.0,
                       moe_period=2)
        dp = moe_init(dc, jax.random.key(5))
        prompt = jnp.array([[9, 4, 17, 2]], jnp.int32)
        ref = generate(mp, prompt, mc, max_new_tokens=10, max_seq=96)
        spec = speculative_generate(mp, dp, prompt, mc, draft_config=dc,
                                    max_new_tokens=10, gamma=3, max_seq=96)
        assert spec.tolist() == ref.tolist()

    def test_draft_max_seq_bounds_capacity(self):
        """A draft whose max_seq is smaller than the target's must bound
        the run (its cache would otherwise silently overflow)."""
        c = _cfg(max_seq=256)
        dc = _cfg(max_seq=32, n_layers=1)
        params = init_params(c, jax.random.key(0))
        draft = init_params(dc, jax.random.key(1))
        prompt = jnp.zeros((1, 20), jnp.int32)
        with pytest.raises(ValueError):
            speculative_generate(params, draft, prompt, c, draft_config=dc,
                                 max_new_tokens=16, gamma=4)
