"""Store semantics: CRUD, optimistic concurrency, status subresource,
finalizer-gated deletion, watches, label selection, persistence/resume.

These are the API-server behaviors the reference operator assumes of
Kubernetes (SURVEY.md §4's envtest layer); everything downstream builds on
them, so they are pinned exhaustively here.
"""

import pytest

from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    ComposableResourceSpec,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.runtime.store import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    Store,
)


def req(name="req-1", size=4) -> ComposabilityRequest:
    return ComposabilityRequest(
        metadata=ObjectMeta(name=name),
        spec=ComposabilityRequestSpec(
            resource=ResourceDetails(type="tpu", model="tpu-v4", size=size)
        ),
    )


def res(name="tpu-1", node="worker-0") -> ComposableResource:
    return ComposableResource(
        metadata=ObjectMeta(name=name),
        spec=ComposableResourceSpec(type="tpu", model="tpu-v4", target_node=node),
    )


class TestCrud:
    def test_create_assigns_system_fields(self, store):
        created = store.create(req())
        assert created.metadata.uid
        assert created.metadata.resource_version > 0
        assert created.metadata.generation == 1
        assert created.metadata.creation_timestamp

    def test_create_duplicate_rejected(self, store):
        store.create(req())
        with pytest.raises(AlreadyExistsError):
            store.create(req())

    def test_get_missing_raises(self, store):
        with pytest.raises(NotFoundError):
            store.get(ComposabilityRequest, "nope")
        assert store.try_get(ComposabilityRequest, "nope") is None

    def test_get_returns_isolated_copy(self, store):
        store.create(req())
        a = store.get(ComposabilityRequest, "req-1")
        a.spec.resource.size = 123
        b = store.get(ComposabilityRequest, "req-1")
        assert b.spec.resource.size == 4

    def test_list_by_label(self, store):
        r1, r2 = res("a"), res("b")
        r1.metadata.labels["app.kubernetes.io/managed-by"] = "req-1"
        r2.metadata.labels["app.kubernetes.io/managed-by"] = "req-2"
        store.create(r1)
        store.create(r2)
        got = store.list(
            ComposableResource, label_selector={"app.kubernetes.io/managed-by": "req-1"}
        )
        assert [o.metadata.name for o in got] == ["a"]

    def test_update_bumps_generation_only_on_spec_change(self, store):
        store.create(req())
        obj = store.get(ComposabilityRequest, "req-1")
        obj.metadata.labels["x"] = "y"
        obj = store.update(obj)
        assert obj.metadata.generation == 1  # metadata-only change
        obj.spec.resource.size = 8
        obj = store.update(obj)
        assert obj.metadata.generation == 2

    def test_conflict_on_stale_resource_version(self, store):
        store.create(req())
        a = store.get(ComposabilityRequest, "req-1")
        b = store.get(ComposabilityRequest, "req-1")
        a.spec.resource.size = 8
        store.update(a)
        b.spec.resource.size = 16
        with pytest.raises(ConflictError):
            store.update(b)


class TestStatusSubresource:
    def test_update_ignores_status(self, store):
        store.create(req())
        obj = store.get(ComposabilityRequest, "req-1")
        obj.status.state = "Running"
        store.update(obj)  # status change must NOT persist through update()
        assert store.get(ComposabilityRequest, "req-1").status.state == ""

    def test_update_status_ignores_spec(self, store):
        store.create(req())
        obj = store.get(ComposabilityRequest, "req-1")
        obj.status.state = "NodeAllocating"
        obj.spec.resource.size = 99
        store.update_status(obj)
        back = store.get(ComposabilityRequest, "req-1")
        assert back.status.state == "NodeAllocating"
        assert back.spec.resource.size == 4

    def test_update_status_conflict(self, store):
        store.create(req())
        a = store.get(ComposabilityRequest, "req-1")
        store.update_status(a)
        with pytest.raises(ConflictError):
            store.update_status(a)


class TestFinalizerDeletion:
    def test_delete_without_finalizers_purges(self, store):
        store.create(req())
        store.delete(ComposabilityRequest, "req-1")
        assert store.try_get(ComposabilityRequest, "req-1") is None

    def test_delete_with_finalizer_marks_terminating(self, store):
        obj = req()
        obj.add_finalizer("tpu.composer.dev/finalizer")
        store.create(obj)
        store.delete(ComposabilityRequest, "req-1")
        got = store.get(ComposabilityRequest, "req-1")
        assert got.being_deleted
        # second delete is a no-op, not an error
        store.delete(ComposabilityRequest, "req-1")

    def test_removing_last_finalizer_purges(self, store):
        obj = req()
        obj.add_finalizer("f")
        store.create(obj)
        store.delete(ComposabilityRequest, "req-1")
        got = store.get(ComposabilityRequest, "req-1")
        got.remove_finalizer("f")
        store.update(got)
        assert store.try_get(ComposabilityRequest, "req-1") is None


class TestWatch:
    def test_watch_sees_lifecycle(self, store):
        q = store.watch("ComposabilityRequest")
        store.create(req())
        obj = store.get(ComposabilityRequest, "req-1")
        obj.spec.resource.size = 8
        store.update(obj)
        store.delete(ComposabilityRequest, "req-1")
        events = [q.get(timeout=1) for _ in range(3)]
        assert [e.type for e in events] == [ADDED, MODIFIED, DELETED]

    def test_watch_filters_kind(self, store):
        q = store.watch("ComposableResource")
        store.create(req())
        store.create(res())
        ev = q.get(timeout=1)
        assert ev.obj.KIND == "ComposableResource"
        assert q.empty()

    def test_status_update_emits_modified(self, store):
        store.create(req())
        q = store.watch("ComposabilityRequest")
        obj = store.get(ComposabilityRequest, "req-1")
        obj.status.state = "Running"
        store.update_status(obj)
        assert q.get(timeout=1).type == MODIFIED


class TestAdmission:
    def test_admission_can_reject(self, store):
        def deny(op, new, old):
            if op == "CREATE" and new.spec.resource.size > 8:
                raise ValueError("too big")

        store.register_admission("ComposabilityRequest", deny)
        store.create(req(size=8))
        with pytest.raises(ValueError):
            store.create(req(name="big", size=16))

    def test_admission_can_mutate(self, store):
        def default_model(op, new, old):
            if not new.spec.resource.model:
                new.spec.resource.model = "tpu-v4"

        store.register_admission("*", default_model)
        r = req()
        r.spec.resource.model = ""
        created = store.create(r)
        assert created.spec.resource.model == "tpu-v4"


class TestPersistence:
    def test_restart_resumes_state(self, tmp_path):
        """CRD-as-checkpoint (SURVEY.md §5): restart resumes mid-state-machine."""
        state = str(tmp_path / "state")
        s1 = Store(persist_dir=state)
        obj = req()
        obj.add_finalizer("f")
        s1.create(obj)
        got = s1.get(ComposabilityRequest, "req-1")
        got.status.state = "NodeAllocating"
        s1.update_status(got)
        rv = s1.get(ComposabilityRequest, "req-1").metadata.resource_version

        s2 = Store(persist_dir=state)
        back = s2.get(ComposabilityRequest, "req-1")
        assert back.status.state == "NodeAllocating"
        assert back.metadata.resource_version == rv
        assert back.has_finalizer("f")
        # resourceVersion counter resumes past the old max
        s2.create(req(name="req-2"))
        assert s2.get(ComposabilityRequest, "req-2").metadata.resource_version > rv

    def test_purge_removes_file(self, tmp_path):
        state = str(tmp_path / "state")
        s1 = Store(persist_dir=state)
        s1.create(req())
        s1.delete(ComposabilityRequest, "req-1")
        s2 = Store(persist_dir=state)
        assert s2.try_get(ComposabilityRequest, "req-1") is None
