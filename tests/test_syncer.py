"""UpstreamSyncer drift repair: grace tracking, detach-CR creation, and the
full leak-reclaim loop through the resource controller (reference:
upstreamsyncer_controller_test.go's 16 entries, SURVEY.md §3.5)."""

import pytest

from tpu_composer.api import ComposableResource, Node, ObjectMeta
from tpu_composer.api.types import LABEL_READY_TO_DETACH
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers.resource_controller import ComposableResourceReconciler
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.store import Store


@pytest.fixture()
def world():
    store = Store()
    for i in range(2):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = InMemoryPool()
    syncer = UpstreamSyncer(store, pool, period=0.01, grace=100.0)
    return store, pool, syncer


class TestDriftTracking:
    def test_leak_tracked_but_not_acted_on_before_grace(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        created = syncer.sync_once(now=0.0)
        assert created == 0
        assert leaked in syncer.tracked_missing
        assert store.list(ComposableResource) == []

    def test_detach_cr_created_after_grace(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        created = syncer.sync_once(now=101.0)
        assert created == 1
        (cr,) = store.list(ComposableResource)
        assert cr.metadata.labels[LABEL_READY_TO_DETACH] == leaked
        assert cr.spec.force_detach
        assert cr.spec.target_node == "worker-1"
        # no duplicate on the next pass
        assert syncer.sync_once(now=102.0) == 0

    def test_suspended_syncer_freezes_grace_clocks(self, world):
        """ISSUE-16 ride-through: while the store breaker is open the
        diff is known-stale — no detach-CRs, and suspension is FROZEN
        time: a pre-outage orphan must re-age a full grace after heal."""
        store, pool, _ = world
        dark = [False]
        syncer = UpstreamSyncer(store, pool, period=0.01, grace=100.0,
                                suspend=lambda: dark[0])
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        assert leaked in syncer.tracked_missing
        dark[0] = True
        # Grace would have LONG expired — but the store is dark, so the
        # clock freezes (re-stamped each suspended pass) and nothing acts.
        assert syncer.sync_once(now=500.0) == 0
        assert store.list(ComposableResource) == []
        dark[0] = False
        # Healed: the orphan's clock restarted at the last dark pass —
        # still inside the fresh grace, then reclaimed once it re-ages.
        assert syncer.sync_once(now=501.0) == 0
        assert syncer.sync_once(now=601.0) == 1

    def test_locally_owned_devices_not_flagged(self, world):
        store, pool, syncer = world
        pool.reserve_slice("s1", "tpu-v4", "2x2x1", ["worker-0"])
        res = ComposableResource(metadata=ObjectMeta(name="r0"))
        res.spec.type, res.spec.model, res.spec.target_node = "tpu", "tpu-v4", "worker-0"
        res.spec.chip_count, res.spec.slice_name, res.spec.topology = 4, "s1", "2x2x1"
        out = pool.add_resource(res)
        res.status.device_ids = out.device_ids
        store.create(res)
        created_obj = store.get(ComposableResource, "r0")
        created_obj.status.device_ids = out.device_ids
        store.update_status(created_obj)
        syncer.sync_once(now=0.0)
        assert syncer.tracked_missing == {}
        assert syncer.sync_once(now=1000.0) == 0

    def test_vanished_leak_stops_tracking(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        # Reclaim behind the syncer's back.
        cr = ComposableResource(metadata=ObjectMeta(name="manual"))
        cr.spec.type, cr.spec.model, cr.spec.target_node = "tpu", "tpu-v4", "worker-1"
        cr.status.device_ids = [leaked]
        pool.remove_resource(cr)
        syncer.sync_once(now=50.0)
        assert syncer.tracked_missing == {}

    def test_reappeared_local_owner_clears_tracking(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        cr = ComposableResource(metadata=ObjectMeta(name="late-owner"))
        cr.spec.type, cr.spec.model, cr.spec.target_node = "tpu", "tpu-v4", "worker-1"
        store.create(cr)
        got = store.get(ComposableResource, "late-owner")
        got.status.device_ids = [leaked]
        store.update_status(got)
        syncer.sync_once(now=50.0)
        assert syncer.tracked_missing == {}


class TestEndToEndReclaim:
    def test_leak_reclaimed_through_detach_path(self, world):
        store, pool, syncer = world
        agent = FakeNodeAgent(pool=pool)
        rec = ComposableResourceReconciler(store, pool, agent)
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        free_before = pool.free_chips("tpu-v4")
        syncer.sync_once(now=0.0)
        syncer.sync_once(now=200.0)  # creates detach-CR
        (cr,) = store.list(ComposableResource)
        for _ in range(8):
            if store.try_get(ComposableResource, cr.metadata.name) is None:
                break
            rec.reconcile(cr.metadata.name)
        assert store.try_get(ComposableResource, cr.metadata.name) is None
        assert pool.free_chips("tpu-v4") == free_before + 1
        assert syncer.sync_once(now=300.0) == 0  # world converged


class TestOrphanOnDeadNode:
    def test_node_gone_orphan_fully_reclaimed(self, world):
        """Node-gone GC purges the CR but leaves the fabric attachment; the
        syncer's detach-CR (targeting the dead node) must still run the
        fabric detach and return the chips to the pool."""
        store, pool, syncer = world
        agent = FakeNodeAgent(pool=pool)
        rec = ComposableResourceReconciler(store, pool, agent)
        pool.reserve_slice("s1", "tpu-v4", "2x2x1", ["worker-1"])
        res = ComposableResource(metadata=ObjectMeta(name="r0"))
        res.spec.type, res.spec.model, res.spec.target_node = "tpu", "tpu-v4", "worker-1"
        res.spec.chip_count, res.spec.slice_name, res.spec.topology = 4, "s1", "2x2x1"
        store.create(res)
        rec.reconcile("r0")
        rec.reconcile("r0")
        assert store.get(ComposableResource, "r0").status.state == "Online"

        store.delete(Node, "worker-1")
        for _ in range(5):
            if store.try_get(ComposableResource, "r0") is None:
                break
            rec.reconcile("r0")
        assert store.try_get(ComposableResource, "r0") is None
        # fabric still holds the chips -> syncer repairs
        assert len(pool.get_resources()) == 4
        syncer.sync_once(now=0.0)
        assert syncer.sync_once(now=200.0) == 4  # one detach-CR per chip
        for cr in store.list(ComposableResource):
            for _ in range(6):
                if store.try_get(ComposableResource, cr.metadata.name) is None:
                    break
                rec.reconcile(cr.metadata.name)
        pool.release_slice("s1")
        assert pool.free_chips("tpu-v4") == 64
        assert pool.get_resources() == []
        # converged: no more detach-CRs get created
        assert syncer.sync_once(now=400.0) == 0


class TestExplicitDeviceType:
    """Satellite (ISSUE 5): the detach-CR's device type comes from the
    fabric's explicit ``FabricDevice.type``, not a model-name prefix sniff
    — the sniff survives only as the fallback for providers that predate
    the field."""

    def test_explicit_type_wins_over_model_name(self, world):
        store, _, _ = world
        # A TPU whose marketing name doesn't start with "tpu": the sniff
        # would misclassify it as gpu; the explicit type must not.
        pool = InMemoryPool(chips={"trillium": 4})
        syncer = UpstreamSyncer(store, pool, grace=10.0)
        leaked = pool.leak_attachment("worker-1", "trillium", type="tpu")
        syncer.sync_once(now=0.0)
        assert syncer.sync_once(now=100.0) == 1
        (cr,) = store.list(ComposableResource)
        assert cr.metadata.labels[LABEL_READY_TO_DETACH] == leaked
        assert cr.spec.type == "tpu"
        assert cr.spec.model == "trillium"

    def test_model_sniff_is_only_the_fallback(self, world):
        store, pool, syncer = world
        from tpu_composer.fabric.provider import FabricDevice

        dev = FabricDevice(device_id="x", node="worker-1", model="tpu-v4")
        assert dev.type == ""  # legacy provider: field absent
        assert syncer._create_detach_cr(dev)
        (cr,) = store.list(ComposableResource)
        assert cr.spec.type == "tpu"  # sniffed, as before


class TestDurableOrphanGrace:
    """Satellite (ISSUE 5): the orphan first-seen timestamp is persisted,
    so a controller restart RESUMES the 10-min grace clock instead of
    resetting it — a crash-loop can no longer defer leak reclamation
    forever."""

    def test_first_seen_persisted_as_tracker(self, world):
        store, pool, syncer = world
        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.api.types import ANNOTATION_ORPHAN_FIRST_SEEN
        from tpu_composer.controllers.syncer import (
            is_orphan_tracker,
            orphan_tracker_name,
        )

        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        rule = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        assert is_orphan_tracker(rule)
        assert rule.spec.device_uuid == leaked
        assert rule.metadata.annotations[ANNOTATION_ORPHAN_FIRST_SEEN]
        # Scheduling-inert: never a whole-node quarantine marker.
        from tpu_composer.agent.publisher import (
            is_node_quarantine_marker,
            quarantined_nodes,
        )

        assert not is_node_quarantine_marker(rule)
        assert quarantined_nodes(store) == set()

    def test_restart_resumes_grace_clock(self, world):
        """A device already aged past grace at restart is reclaimed on the
        NEW syncer's first pass — no fresh 10-minute wait."""
        store, pool, syncer = world
        import time as _time

        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.api.types import ANNOTATION_ORPHAN_FIRST_SEEN
        from tpu_composer.controllers.syncer import orphan_tracker_name

        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)  # persists first-seen
        # Age the durable record past the grace window (grace=100 in the
        # fixture), as if the crash-loop had been churning for 150 s.
        rule = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        stamp = (
            __import__("datetime").datetime.fromtimestamp(
                _time.time() - 150.0, __import__("datetime").timezone.utc
            ).isoformat().replace("+00:00", "Z")
        )
        rule.metadata.annotations[ANNOTATION_ORPHAN_FIRST_SEEN] = stamp
        store.update(rule)

        fresh = UpstreamSyncer(store, pool, grace=100.0)  # the restart
        assert fresh.sync_once(now=1000.0) == 1, (
            "restart reset the grace clock instead of resuming it"
        )
        (cr,) = store.list(ComposableResource)
        assert cr.metadata.labels[LABEL_READY_TO_DETACH] == leaked
        # Tracker retired with the reclamation.
        assert store.try_get(
            DeviceTaintRule, orphan_tracker_name(leaked)) is None

    def test_restart_without_aging_still_waits_out_grace(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        fresh = UpstreamSyncer(store, pool, grace=100.0)
        assert fresh.sync_once(now=0.0) == 0  # age ~0: grace still runs
        assert leaked in fresh.tracked_missing

    def test_reappeared_owner_drops_tracker(self, world):
        store, pool, syncer = world
        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.controllers.syncer import orphan_tracker_name

        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        assert store.try_get(
            DeviceTaintRule, orphan_tracker_name(leaked)) is not None
        cr = ComposableResource(metadata=ObjectMeta(name="late-owner"))
        cr.spec.type, cr.spec.model, cr.spec.target_node = (
            "tpu", "tpu-v4", "worker-1")
        store.create(cr)
        got = store.get(ComposableResource, "late-owner")
        got.status.device_ids = [leaked]
        store.update_status(got)
        syncer.sync_once(now=50.0)
        assert store.try_get(
            DeviceTaintRule, orphan_tracker_name(leaked)) is None

    def test_unreadable_stamp_restarts_clock_but_keeps_tracking(self, world):
        store, pool, syncer = world
        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.api.types import ANNOTATION_ORPHAN_FIRST_SEEN
        from tpu_composer.controllers.syncer import orphan_tracker_name

        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        rule = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        rule.metadata.annotations[ANNOTATION_ORPHAN_FIRST_SEEN] = "not-a-time"
        store.update(rule)
        fresh = UpstreamSyncer(store, pool, grace=100.0)
        assert fresh.sync_once(now=0.0) == 0
        assert leaked in fresh.tracked_missing  # tracked, clock restarted
        assert fresh.sync_once(now=150.0) == 1  # and still reclaims

    def test_failed_tracker_load_is_retried_next_tick(self, world):
        """A transient list failure on the first tick must not permanently
        disable clock resumption: the next tick retries the load and the
        durable age still wins over the reset in-memory clock."""
        store, pool, syncer = world
        import time as _time

        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.api.types import ANNOTATION_ORPHAN_FIRST_SEEN
        from tpu_composer.controllers.syncer import orphan_tracker_name
        from tpu_composer.runtime.chaosstore import ChaosStore

        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)  # persists the first-seen record
        rule = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        stamp = (
            __import__("datetime").datetime.fromtimestamp(
                _time.time() - 150.0, __import__("datetime").timezone.utc
            ).isoformat().replace("+00:00", "Z")
        )
        rule.metadata.annotations[ANNOTATION_ORPHAN_FIRST_SEEN] = stamp
        store.update(rule)

        chaos = ChaosStore(store)
        chaos.fail_verb("list", 1)  # the restart's tracker load fails
        fresh = UpstreamSyncer(chaos, pool, grace=100.0)
        assert fresh.sync_once(now=1000.0) == 0  # load failed; clock reset
        # Next tick: the load retry lands and the 150 s durable age
        # (> grace 100) reclaims immediately — no fresh grace wait.
        assert fresh.sync_once(now=1001.0) == 1, (
            "one transient list failure permanently disabled clock resume"
        )

    def test_failed_tracker_persist_is_retried_backdated(self, world):
        """A transient create failure when a device is first seen missing
        must be retried on later ticks, back-dated to the in-memory
        first-seen time — not silently skipped forever."""
        store, pool, _ = world
        import time as _time

        from tpu_composer.api.dra import DeviceTaintRule
        from tpu_composer.api.meta import parse_iso
        from tpu_composer.api.types import ANNOTATION_ORPHAN_FIRST_SEEN
        from tpu_composer.controllers.syncer import orphan_tracker_name
        from tpu_composer.runtime.chaosstore import ChaosStore

        chaos = ChaosStore(store)
        syncer = UpstreamSyncer(chaos, pool, grace=100.0)
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        chaos.fail_verb("create", 1)
        syncer.sync_once(now=0.0)  # first sighting; persist fails
        assert store.try_get(
            DeviceTaintRule, orphan_tracker_name(leaked)) is None
        syncer.sync_once(now=40.0)  # retry lands, back-dated 40 s
        rule = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        age = _time.time() - parse_iso(
            rule.metadata.annotations[ANNOTATION_ORPHAN_FIRST_SEEN]
        ).timestamp()
        assert 35.0 <= age <= 60.0, (
            f"stamp not back-dated to first-seen (age {age:.1f}s, want ~40)"
        )
        # No further re-stamping once persisted.
        syncer.sync_once(now=50.0)
        rule2 = store.get(DeviceTaintRule, orphan_tracker_name(leaked))
        assert rule2.metadata.annotations == rule.metadata.annotations
