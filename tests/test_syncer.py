"""UpstreamSyncer drift repair: grace tracking, detach-CR creation, and the
full leak-reclaim loop through the resource controller (reference:
upstreamsyncer_controller_test.go's 16 entries, SURVEY.md §3.5)."""

import pytest

from tpu_composer.api import ComposableResource, Node, ObjectMeta
from tpu_composer.api.types import LABEL_READY_TO_DETACH
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.controllers.resource_controller import ComposableResourceReconciler
from tpu_composer.controllers.syncer import UpstreamSyncer
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime.store import Store


@pytest.fixture()
def world():
    store = Store()
    for i in range(2):
        n = Node(metadata=ObjectMeta(name=f"worker-{i}"))
        n.status.tpu_slots = 8
        store.create(n)
    pool = InMemoryPool()
    syncer = UpstreamSyncer(store, pool, period=0.01, grace=100.0)
    return store, pool, syncer


class TestDriftTracking:
    def test_leak_tracked_but_not_acted_on_before_grace(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        created = syncer.sync_once(now=0.0)
        assert created == 0
        assert leaked in syncer.tracked_missing
        assert store.list(ComposableResource) == []

    def test_detach_cr_created_after_grace(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        created = syncer.sync_once(now=101.0)
        assert created == 1
        (cr,) = store.list(ComposableResource)
        assert cr.metadata.labels[LABEL_READY_TO_DETACH] == leaked
        assert cr.spec.force_detach
        assert cr.spec.target_node == "worker-1"
        # no duplicate on the next pass
        assert syncer.sync_once(now=102.0) == 0

    def test_locally_owned_devices_not_flagged(self, world):
        store, pool, syncer = world
        pool.reserve_slice("s1", "tpu-v4", "2x2x1", ["worker-0"])
        res = ComposableResource(metadata=ObjectMeta(name="r0"))
        res.spec.type, res.spec.model, res.spec.target_node = "tpu", "tpu-v4", "worker-0"
        res.spec.chip_count, res.spec.slice_name, res.spec.topology = 4, "s1", "2x2x1"
        out = pool.add_resource(res)
        res.status.device_ids = out.device_ids
        store.create(res)
        created_obj = store.get(ComposableResource, "r0")
        created_obj.status.device_ids = out.device_ids
        store.update_status(created_obj)
        syncer.sync_once(now=0.0)
        assert syncer.tracked_missing == {}
        assert syncer.sync_once(now=1000.0) == 0

    def test_vanished_leak_stops_tracking(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        # Reclaim behind the syncer's back.
        cr = ComposableResource(metadata=ObjectMeta(name="manual"))
        cr.spec.type, cr.spec.model, cr.spec.target_node = "tpu", "tpu-v4", "worker-1"
        cr.status.device_ids = [leaked]
        pool.remove_resource(cr)
        syncer.sync_once(now=50.0)
        assert syncer.tracked_missing == {}

    def test_reappeared_local_owner_clears_tracking(self, world):
        store, pool, syncer = world
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        syncer.sync_once(now=0.0)
        cr = ComposableResource(metadata=ObjectMeta(name="late-owner"))
        cr.spec.type, cr.spec.model, cr.spec.target_node = "tpu", "tpu-v4", "worker-1"
        store.create(cr)
        got = store.get(ComposableResource, "late-owner")
        got.status.device_ids = [leaked]
        store.update_status(got)
        syncer.sync_once(now=50.0)
        assert syncer.tracked_missing == {}


class TestEndToEndReclaim:
    def test_leak_reclaimed_through_detach_path(self, world):
        store, pool, syncer = world
        agent = FakeNodeAgent(pool=pool)
        rec = ComposableResourceReconciler(store, pool, agent)
        leaked = pool.leak_attachment("worker-1", "tpu-v4")
        free_before = pool.free_chips("tpu-v4")
        syncer.sync_once(now=0.0)
        syncer.sync_once(now=200.0)  # creates detach-CR
        (cr,) = store.list(ComposableResource)
        for _ in range(8):
            if store.try_get(ComposableResource, cr.metadata.name) is None:
                break
            rec.reconcile(cr.metadata.name)
        assert store.try_get(ComposableResource, cr.metadata.name) is None
        assert pool.free_chips("tpu-v4") == free_before + 1
        assert syncer.sync_once(now=300.0) == 0  # world converged


class TestOrphanOnDeadNode:
    def test_node_gone_orphan_fully_reclaimed(self, world):
        """Node-gone GC purges the CR but leaves the fabric attachment; the
        syncer's detach-CR (targeting the dead node) must still run the
        fabric detach and return the chips to the pool."""
        store, pool, syncer = world
        agent = FakeNodeAgent(pool=pool)
        rec = ComposableResourceReconciler(store, pool, agent)
        pool.reserve_slice("s1", "tpu-v4", "2x2x1", ["worker-1"])
        res = ComposableResource(metadata=ObjectMeta(name="r0"))
        res.spec.type, res.spec.model, res.spec.target_node = "tpu", "tpu-v4", "worker-1"
        res.spec.chip_count, res.spec.slice_name, res.spec.topology = 4, "s1", "2x2x1"
        store.create(res)
        rec.reconcile("r0")
        rec.reconcile("r0")
        assert store.get(ComposableResource, "r0").status.state == "Online"

        store.delete(Node, "worker-1")
        for _ in range(5):
            if store.try_get(ComposableResource, "r0") is None:
                break
            rec.reconcile("r0")
        assert store.try_get(ComposableResource, "r0") is None
        # fabric still holds the chips -> syncer repairs
        assert len(pool.get_resources()) == 4
        syncer.sync_once(now=0.0)
        assert syncer.sync_once(now=200.0) == 4  # one detach-CR per chip
        for cr in store.list(ComposableResource):
            for _ in range(6):
                if store.try_get(ComposableResource, cr.metadata.name) is None:
                    break
                rec.reconcile(cr.metadata.name)
        pool.release_slice("s1")
        assert pool.free_chips("tpu-v4") == 64
        assert pool.get_resources() == []
        # converged: no more detach-CRs get created
        assert syncer.sync_once(now=400.0) == 0
