"""Slice shape solver: valid shapes, host math, rejection of bad counts."""

import pytest

from tpu_composer.topology import SliceShape, TopologyError, solve_slice, is_tpu_model


class TestSolve:
    def test_single_chip(self):
        s = solve_slice("tpu-v4", 1)
        assert s.num_chips == 1 and s.num_hosts == 1 and s.chips_per_host == 1

    def test_two_chips_standalone(self):
        s = solve_slice("tpu-v4", 2)
        assert s.num_hosts == 1

    def test_single_host_v4_8(self):
        # BASELINE config[2]: count=4 → single-host 2x2 slice
        s = solve_slice("tpu-v4", 4)
        assert sorted(s.dims) == [1, 2, 2]
        assert s.num_hosts == 1 and s.chips_per_host == 4

    def test_two_host_slice(self):
        s = solve_slice("tpu-v4", 8)
        assert s.num_hosts == 2
        assert sorted(s.dims) == [2, 2, 2]

    def test_pod_slice_32(self):
        # BASELINE config[3]: multi-host pod slice
        s = solve_slice("tpu-v4", 32)
        assert s.num_hosts == 8
        prod = 1
        for d in s.dims:
            prod *= d
        assert prod == 32
        # compactness: prefers 2x4x4 over 2x2x8
        assert sorted(s.dims) == [2, 4, 4]

    def test_explicit_topology_pinned(self):
        s = solve_slice("tpu-v4", 16, topology="2x2x4")
        assert s.dims == (2, 2, 4)
        assert s.num_hosts == 4

    def test_explicit_topology_wrong_count_rejected(self):
        with pytest.raises(TopologyError):
            solve_slice("tpu-v4", 8, topology="2x2x4")

    def test_invalid_topology_shape_rejected(self):
        # 1x1x16 is not a valid torus for 16 chips (dims must be >=2)
        with pytest.raises(TopologyError):
            solve_slice("tpu-v4", 16, topology="1x1x16")

    def test_non_tileable_count_rejected_with_suggestions(self):
        with pytest.raises(TopologyError) as ei:
            solve_slice("tpu-v4", 6)
        assert "nearby valid counts" in str(ei.value)

    def test_v5e_is_2d(self):
        s = solve_slice("tpu-v5e", 16)
        assert len(s.dims) == 2
        assert s.num_hosts == 2 and s.chips_per_host == 8

    def test_v5e_standalone_4(self):
        s = solve_slice("tpu-v5e", 4)
        assert s.num_hosts == 1

    def test_unknown_model(self):
        with pytest.raises(TopologyError):
            solve_slice("tpu-v99", 4)

    def test_over_max_rejected(self):
        with pytest.raises(TopologyError):
            solve_slice("tpu-v5e", 512)

    def test_worker_chip_indices(self):
        s = solve_slice("tpu-v4", 8)
        assert s.worker_chip_indices(0) == [0, 1, 2, 3]
        assert s.worker_chip_indices(1) == [4, 5, 6, 7]

    def test_is_tpu_model(self):
        assert is_tpu_model("tpu-v4")
        assert not is_tpu_model("gpu-a100")

    def test_topology_string(self):
        assert solve_slice("tpu-v4", 16, topology="2x2x4").topology == "2x2x4"
