"""Control-plane tracing (runtime/tracing.py) — a subsystem the reference
lacks entirely (SURVEY.md §5: no pprof, no otel). Spans over reconciles and
fabric verbs, nested via a thread-local stack, exported as Chrome
trace-event JSON from the health server's /debug/traces."""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposableResource,
    ComposableResourceSpec,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    RequestTiming,
    ResourceTiming,
)
from tpu_composer.fabric.adapter import TracedFabricProvider
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime import tracing
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.store import Store


@pytest.fixture(autouse=True)
def _fresh_ring():
    tracing.reset()
    yield
    tracing.reset()


class TestSpans:
    def test_span_records_duration_and_attrs(self):
        with tracing.span("work", cat="test", object="x") as sp:
            sp["outcome"] = "ok"
        (evt,) = tracing.snapshot()
        assert evt["name"] == "work" and evt["cat"] == "test"
        assert evt["ph"] == "X" and evt["dur"] >= 0
        assert evt["args"]["object"] == "x"
        assert evt["args"]["outcome"] == "ok"

    def test_nesting_links_parent(self):
        with tracing.span("outer"):
            with tracing.span("inner"):
                pass
        inner, outer = tracing.snapshot()  # inner closes first
        assert inner["name"] == "inner"
        assert inner["args"]["parent_span"] == outer["id"]
        assert "parent_span" not in outer["args"]

    def test_exception_recorded_and_reraised(self):
        with pytest.raises(ValueError):
            with tracing.span("boom"):
                raise ValueError("nope")
        (evt,) = tracing.snapshot()
        assert "ValueError" in evt["args"]["error"]

    def test_ring_is_bounded(self):
        tracing.configure(100)
        try:
            for i in range(250):
                with tracing.span(f"s{i}"):
                    pass
            events = tracing.snapshot()
            assert len(events) == 100
            assert events[-1]["name"] == "s249"  # newest kept, oldest gone
        finally:
            tracing.configure(10_000)

    def test_threads_do_not_cross_link(self):
        done = threading.Event()

        def other():
            with tracing.span("other-thread"):
                done.wait(2)

        t = threading.Thread(target=other)
        with tracing.span("main-thread"):
            t.start()
            done.set()
            t.join()
        by_name = {e["name"]: e for e in tracing.snapshot()}
        assert "parent_span" not in by_name["other-thread"]["args"]

    def test_chrome_export_shape(self):
        with tracing.span("a"):
            pass
        doc = json.loads(tracing.export_chrome())
        assert doc["traceEvents"][0]["ph"] == "X"

    def test_summarize(self):
        for _ in range(3):
            with tracing.span("repeat", cat="c1"):
                pass
        s = tracing.summarize(cat="c1")
        assert s["repeat"]["count"] == 3
        assert s["repeat"]["total_ms"] >= s["repeat"]["max_ms"]


class TestWiring:
    def test_fabric_wrapper_spans_every_verb(self):
        pool = TracedFabricProvider(InMemoryPool())
        pool.reserve_slice("s", "tpu-v4", "1x2x2", ["n0"])
        pool.get_resources()
        pool.release_slice("s")
        names = [e["name"] for e in tracing.snapshot()]
        assert names == [
            "fabric.reserve_slice", "fabric.get_resources",
            "fabric.release_slice",
        ]
        assert all(
            e["args"]["provider"] == "InMemoryPool" for e in tracing.snapshot()
        )

    def test_fabric_wrapper_caches_traced_verbs(self):
        """Verb wrappers are built once per instance — repeat access is a
        plain __dict__ hit (no closure rebuild on the attach hot path) and
        still records spans; non-verb instrumentation stays a live read."""
        pool = TracedFabricProvider(InMemoryPool())
        first = pool.get_resources
        assert pool.get_resources is first  # cached, not rebuilt
        assert "get_resources" in pool.__dict__
        first()
        first()
        names = [e["name"] for e in tracing.snapshot()]
        assert names.count("fabric.get_resources") == 2
        assert "free_chips" not in pool.__dict__  # passthrough not cached
        assert pool.free_chips("tpu-v4") == pool._inner.free_chips("tpu-v4")

    def test_reconcile_spans_nest_fabric_calls_and_serve_over_http(self):
        store = Store()
        n = Node(metadata=ObjectMeta(name="worker-0"))
        n.status.tpu_slots = 4
        store.create(n)
        pool = TracedFabricProvider(InMemoryPool())
        mgr = Manager(store=store, health_addr="127.0.0.1:0")
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool, timing=RequestTiming(updating_poll=0.02)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, FakeNodeAgent(pool=pool._inner),
            timing=ResourceTiming(attach_poll=0.02, visibility_poll=0.02,
                                  detach_poll=0.02, detach_fast=0.02,
                                  busy_poll=0.02)))
        mgr.start()
        try:
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name="traced"),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4)),
            ))
            import time
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if store.get(ComposabilityRequest, "traced").status.state == "Running":
                    break
                time.sleep(0.01)
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mgr.health_port}/debug/traces").read())
            events = doc["traceEvents"]
            recs = [e for e in events if e["name"] == "reconcile"]
            fabs = [e for e in events if e["name"].startswith("fabric.")]
            assert recs and fabs
            # A fabric call made inside a reconcile carries that span as
            # its parent — the nesting that makes the trace readable.
            rec_ids = {e["id"] for e in recs}
            assert any(f["args"].get("parent_span") in rec_ids for f in fabs)
            summary = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mgr.health_port}/debug/traces/summary"
            ).read())
            assert summary["reconcile"]["count"] >= 1
        finally:
            mgr.stop()

    def test_trace_file_written_on_stop(self, tmp_path, monkeypatch):
        path = tmp_path / "trace.json"
        monkeypatch.setenv("TPUC_TRACE_FILE", str(path))
        mgr = Manager(store=Store())
        mgr.start()
        with tracing.span("before-stop"):
            pass
        mgr.stop()
        doc = json.loads(path.read_text())
        assert any(e["name"] == "before-stop" for e in doc["traceEvents"])


class TestFlows:
    """Cross-thread causality: handoff() emits a flow-start bound to the
    producing span; span(ctx=...) / link() consume it on the other thread —
    Perfetto draws the arrow. The trace_id rides along."""

    def test_handoff_and_consume_draw_one_arrow(self):
        consumed = threading.Event()
        box = {}

        def consumer():
            with tracing.span("consume", cat="t", ctx=box["ctx"]):
                pass
            consumed.set()

        with tracing.span("produce", cat="t"):
            box["ctx"] = tracing.new_trace("trace-1").handoff()
        t = threading.Thread(target=consumer)
        t.start()
        t.join()
        assert consumed.wait(2)
        events = tracing.snapshot()
        starts = [e for e in events if e.get("ph") == "s"]
        finishes = [e for e in events if e.get("ph") == "f"]
        assert len(starts) == 1 and len(finishes) == 1
        assert starts[0]["id"] == finishes[0]["id"]
        assert starts[0]["tid"] != finishes[0]["tid"]  # crossed threads
        spans = {e["name"]: e for e in events if e.get("ph") == "X"}
        assert spans["consume"]["args"]["trace_id"] == "trace-1"

    def test_flow_is_one_shot(self):
        ctx = tracing.new_trace().handoff()
        tracing.link(ctx)
        tracing.link(ctx)  # second consume is a no-op
        finishes = [e for e in tracing.snapshot() if e.get("ph") == "f"]
        assert len(finishes) == 1

    def test_child_spans_and_handoffs_inherit_the_trace(self):
        ctx = tracing.new_trace("inherit-me")
        with tracing.span("outer", ctx=ctx):
            with tracing.span("inner"):
                pass
            hop = tracing.context().handoff()
        assert hop.trace_id == "inherit-me"
        spans = {e["name"]: e for e in tracing.snapshot() if e.get("ph") == "X"}
        assert spans["inner"]["args"]["trace_id"] == "inherit-me"

    def test_adopt_trace_backfills_open_spans(self):
        """The resource controller discovers the pending_op nonce INSIDE
        the already-open reconcile span — adopt_trace must stamp it onto
        every open span retroactively and restore on span exit."""
        with tracing.span("reconcile-like"):
            tracing.adopt_trace(tracing.TraceContext(trace_id="nonce-42"))
            with tracing.span("child"):
                pass
        with tracing.span("next-on-thread"):
            pass
        spans = {e["name"]: e for e in tracing.snapshot() if e.get("ph") == "X"}
        assert spans["reconcile-like"]["args"]["trace_id"] == "nonce-42"
        assert spans["child"]["args"]["trace_id"] == "nonce-42"
        assert "trace_id" not in spans["next-on-thread"]["args"]

    def test_queue_propagates_context_to_dequeuer(self):
        from tpu_composer.runtime.queue import RateLimitingQueue

        q = RateLimitingQueue()
        with tracing.span("producer", ctx=tracing.new_trace("qt-1")):
            q.add("obj")
        assert q.get(timeout=1) == "obj"  # dequeue claims the context
        ctx = q.pop_context("obj")
        assert ctx is not None and ctx.trace_id == "qt-1"
        assert q.pop_context("obj") is None  # consumed
        starts = [e for e in tracing.snapshot() if e.get("ph") == "s"]
        assert starts, "add() inside a span must emit the flow-start"

    def test_adopt_trace_outside_any_span_does_not_leak(self):
        """adopt_trace relies on the enclosing span to restore the
        previous context; with NO span open there is no restore point, so
        it must not persist — a test (or tool) calling reconcile()
        directly would otherwise stamp the leaked trace_id onto every
        later span on that thread."""
        tracing.adopt_trace(tracing.TraceContext(trace_id="leak-1"))
        assert tracing.context() is None
        with tracing.span("after"):
            pass
        (evt,) = [e for e in tracing.snapshot() if e["name"] == "after"]
        assert "trace_id" not in evt["args"]

    def test_queue_forget_keeps_parked_context(self):
        # The completion->requeue arrow's survival path: a context parked
        # by an add() made WHILE the key is processing (a dispatcher
        # completion latch, which also set the dirty bit) belongs to the
        # upcoming dirty-requeued reconcile. Neither the success-path
        # forget() nor the current reconcile's pop_context may consume it
        # — only the requeue's own dequeue claims it.
        from tpu_composer.runtime.queue import RateLimitingQueue

        q = RateLimitingQueue()
        q.add("obj")
        assert q.get(timeout=1) == "obj"     # reconcile in flight, no ctx
        with tracing.span("latch", ctx=tracing.new_trace("qt-2")):
            q.add("obj")                     # completion latch: parks ctx
        assert q.pop_context("obj") is None  # current reconcile: not yours
        q.forget("obj")                      # success path: must not drop
        q.done("obj")                        # dirty -> requeued
        assert q.get(timeout=1) == "obj"
        ctx = q.pop_context("obj")
        assert ctx is not None and ctx.trace_id == "qt-2"

    def test_disabled_records_nothing_but_keeps_trace_ids(self):
        tracing.set_enabled(False)
        try:
            ctx = tracing.new_trace("still-here").handoff()
            assert ctx.trace_id == "still-here"
            with tracing.span("silent", ctx=ctx):
                pass
        finally:
            tracing.set_enabled(True)
        assert tracing.snapshot() == []


class TestConcurrency:
    """The satellite's torture cases: ring resize during active spans and
    nested span() re-entry on concurrent worker threads."""

    def test_ring_resize_during_active_spans(self):
        stop = threading.Event()
        errors = []

        def worker(i):
            try:
                while not stop.is_set():
                    with tracing.span(f"w{i}", cat="stress"):
                        with tracing.span(f"w{i}.child", cat="stress"):
                            pass
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        try:
            for cap in (64, 512, 128, 10_000):
                tracing.configure(cap)
                time.sleep(0.02)
        finally:
            stop.set()
            for t in threads:
                t.join(5)
        assert not errors
        tracing.configure(10_000)
        assert len(tracing.snapshot()) <= 10_000

    def test_nested_reentry_across_worker_threads(self):
        """Each thread's parent links must stay within that thread even
        under concurrent re-entry — a cross-thread parent would make
        Perfetto nest one worker's reconcile under another's."""
        barrier = threading.Barrier(6)

        def worker(i):
            barrier.wait(timeout=5)
            for _ in range(20):
                with tracing.span("outer", cat="reentry"):
                    with tracing.span("mid", cat="reentry"):
                        with tracing.span("leaf", cat="reentry"):
                            pass

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        events = [e for e in tracing.snapshot() if e.get("cat") == "reentry"]
        by_id = {e["id"]: e for e in events}
        for e in events:
            parent = e["args"].get("parent_span")
            if parent is None:
                assert e["name"] == "outer"
                continue
            assert by_id[parent]["tid"] == e["tid"], (
                "parent span recorded on a different thread"
            )
            expected_parent = {"leaf": "mid", "mid": "outer"}[e["name"]]
            assert by_id[parent]["name"] == expected_parent


class TestDebugEndpoints:
    @pytest.fixture()
    def served(self):
        mgr = Manager(store=Store(), health_addr="127.0.0.1:0")
        mgr.start()
        yield mgr
        mgr.stop()

    def _get(self, mgr, path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{mgr.health_port}{path}"
        )

    def test_cat_and_limit_filtering(self, served):
        for i in range(10):
            with tracing.span(f"a{i}", cat="aa"):
                pass
            with tracing.span(f"b{i}", cat="bb"):
                pass
        doc = json.loads(self._get(served, "/debug/traces?cat=aa").read())
        assert {e["cat"] for e in doc["traceEvents"]} == {"aa"}
        doc = json.loads(
            self._get(served, "/debug/traces?cat=bb&limit=3").read()
        )
        assert [e["name"] for e in doc["traceEvents"]] == ["b7", "b8", "b9"]
        # Malformed limit degrades to unlimited rather than erroring.
        doc = json.loads(
            self._get(served, "/debug/traces?limit=bogus").read()
        )
        assert len(doc["traceEvents"]) == 20
        # limit=0 means NONE (events[-0:] would be the full ring).
        doc = json.loads(self._get(served, "/debug/traces?limit=0").read())
        assert doc["traceEvents"] == []

    def test_response_byte_cap_drops_oldest_first(self, served, monkeypatch):
        from tpu_composer.runtime import manager as manager_mod

        for i in range(200):
            with tracing.span(f"s{i:03d}", cat="cap", payload="x" * 50):
                pass
        monkeypatch.setattr(manager_mod, "TRACE_RESPONSE_BYTE_CAP", 5000)
        raw = self._get(served, "/debug/traces?cat=cap").read()
        assert len(raw) <= 6000  # cap + the truncation marker's slack
        doc = json.loads(raw)
        assert doc["truncated"] > 0
        names = [e["name"] for e in doc["traceEvents"]]
        assert names[-1] == "s199", "newest events must survive the cap"

    def test_request_timeline_endpoint(self, served):
        from tpu_composer.runtime import lifecycle

        lifecycle.recorder.record_state(
            "ComposableResource", "timeline-cr", "Attaching",
            trace_id="n-1",
        )
        lifecycle.recorder.record_state(
            "ComposableResource", "timeline-cr", "Online")
        listing = json.loads(self._get(served, "/debug/requests").read())
        assert "timeline-cr" in listing["requests"]
        doc = json.loads(
            self._get(served, "/debug/requests/timeline-cr").read()
        )
        assert doc["phase"] == "Ready" and doc["state"] == "Online"
        phases = [e for e in doc["entries"] if e["t"] == "phase"]
        assert [p["phase"] for p in phases] == ["Attaching", "Ready"]
        assert phases[0]["trace_id"] == "n-1"
        assert phases[1]["prev_phase"] == "Attaching"
        assert phases[1]["prev_phase_s"] >= 0
        with pytest.raises(urllib.error.HTTPError) as err:
            self._get(served, "/debug/requests/no-such-cr")
        assert err.value.code == 404


class TestCausalAcceptance:
    """The PR's acceptance scenario: a single 32-chip batched attach wave
    exports ONE Chrome trace in which every member's spans are connected by
    flow events across threads (reconcile worker -> dispatcher lane ->
    completion requeue), and tpuc_phase_duration_seconds is populated for
    every lifecycle phase the wave visited."""

    def test_32chip_wave_connected_trace_and_phase_histogram(self):
        from tpu_composer.fabric.dispatcher import FabricDispatcher
        from tpu_composer.fabric.inmem import InMemoryPool
        from tpu_composer.runtime import lifecycle
        from tpu_composer.runtime.metrics import phase_duration_seconds

        lifecycle.recorder.reset()
        store = Store()
        node = Node(metadata=ObjectMeta(name="wave-node"))
        node.status.tpu_slots = 36
        store.create(node)
        pool = InMemoryPool(chips={"gpu-a100": 32, "tpu-v4": 4})
        traced = TracedFabricProvider(pool)
        agent = FakeNodeAgent(pool=pool)
        # A generous window so the in-proc submission wave coalesces into
        # group calls — the batched shape the flow assertions target.
        dispatcher = FabricDispatcher(traced, batch_window=0.05,
                                      poll_interval=0.01, concurrency=8)
        mgr = Manager(store=store, dispatcher=dispatcher)
        mgr.add_controller(ComposabilityRequestReconciler(
            store, traced,
            timing=RequestTiming(updating_poll=0.01, cleaning_poll=0.01)))
        mgr.add_controller(ComposableResourceReconciler(
            store, traced, agent, dispatcher=dispatcher,
            timing=ResourceTiming(attach_poll=0.01, visibility_poll=0.01,
                                  detach_poll=0.01, detach_fast=0.01,
                                  busy_poll=0.01)))
        mgr.add_runnable(dispatcher.run)
        mgr.start(workers_per_controller=8)
        members = [f"wave-{i}" for i in range(32)]
        try:
            # The 32-chip wave: 32 single-chip members on ONE node, so the
            # dispatcher's per-node lane batches them into group calls.
            for name in members:
                store.create(ComposableResource(
                    metadata=ObjectMeta(name=name),
                    spec=ComposableResourceSpec(type="gpu", model="gpu-a100",
                                                target_node="wave-node"),
                ))
            # A request alongside, so the request-kind phases populate too.
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name="acc-req"),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4)),
            ))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if (
                    all((r := store.try_get(ComposableResource, m)) is not None
                        and r.status.state == "Online" for m in members)
                    and store.get(ComposabilityRequest,
                                  "acc-req").status.state == "Running"
                ):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("32-chip wave never fully attached")
            # Tear down so the Ready/Detaching/Terminating phases are LEFT
            # (durations are observed on phase exit).
            for m in members:
                store.delete(ComposableResource, m)
            store.delete(ComposabilityRequest, "acc-req")
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if (all(store.try_get(ComposableResource, m) is None
                        for m in members)
                        and store.try_get(ComposabilityRequest,
                                          "acc-req") is None):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("wave teardown never completed")
        finally:
            mgr.stop()
            dispatcher.stop()

        # -- one exported Chrome trace --------------------------------
        doc = json.loads(tracing.export_chrome())
        events = doc["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        flow_s = {e["id"]: e for e in events if e.get("ph") == "s"}
        flow_f = {e["id"]: e for e in events if e.get("ph") == "f"}

        # Each member's attach rode one trace_id (its pending_op nonce).
        member_traces = {}
        for e in spans:
            if (e["name"].startswith("dispatch.complete")
                    and e["args"].get("resource") in members
                    and e["args"].get("verb") == "add"
                    and "trace_id" in e["args"]):
                member_traces.setdefault(e["args"]["resource"],
                                         e["args"]["trace_id"])
        assert len(member_traces) == 32, (
            f"missing completion spans: {sorted(member_traces)}"
        )
        assert len(set(member_traces.values())) == 32  # one trace per member

        three_thread_members = 0
        for name, trace_id in member_traces.items():
            mine = [e for e in events
                    if e.get("args", {}).get("trace_id") == trace_id]
            span_names = {e["name"] for e in mine if e.get("ph") == "X"}
            assert "reconcile" in span_names, (name, span_names)
            assert any(s.startswith("dispatch.add") or s == "dispatch.complete"
                       for s in span_names), (name, span_names)
            # Flow arrows: every matched s/f pair in this trace must cross
            # threads, and there must be at least two (submit -> dispatch,
            # completion -> requeued reconcile).
            pairs = [
                (flow_s[e["id"]], flow_f[e["id"]])
                for e in mine
                if e.get("ph") == "s" and e["id"] in flow_f
            ]
            crossing = [(s, f) for s, f in pairs if s["tid"] != f["tid"]]
            assert len(crossing) >= 2, (
                f"{name}: expected >=2 cross-thread flow arrows, got"
                f" {len(crossing)} of {len(pairs)} pairs"
            )
            tids = {e["tid"] for e in mine if e.get("ph") == "X"}
            if len(tids) >= 3:
                three_thread_members += 1
        # Reconcile worker, dispatcher lane, completion-requeued reconcile:
        # with 8 workers the requeue lands on a different worker for ~7/8
        # of members; requiring half keeps the assertion deterministic.
        assert three_thread_members >= 16, three_thread_members

        # -- phase histogram populated for every visited phase ---------
        seen = {(ls.get("kind"), ls.get("phase"))
                for ls in phase_duration_seconds.label_sets()}
        for phase in ("Pending", "Attaching", "Ready", "Detaching"):
            assert ("resource", phase) in seen, (phase, sorted(seen))
        for phase in ("Pending", "Scheduled", "Ready", "Terminating"):
            assert ("request", phase) in seen, (phase, sorted(seen))
        for kind, phase in seen:
            assert phase_duration_seconds.count(kind=kind, phase=phase) > 0
