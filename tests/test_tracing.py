"""Control-plane tracing (runtime/tracing.py) — a subsystem the reference
lacks entirely (SURVEY.md §5: no pprof, no otel). Spans over reconciles and
fabric verbs, nested via a thread-local stack, exported as Chrome
trace-event JSON from the health server's /debug/traces."""

from __future__ import annotations

import json
import threading
import urllib.request

import pytest

from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    Node,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    RequestTiming,
    ResourceTiming,
)
from tpu_composer.fabric.adapter import TracedFabricProvider
from tpu_composer.fabric.inmem import InMemoryPool
from tpu_composer.runtime import tracing
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.store import Store


@pytest.fixture(autouse=True)
def _fresh_ring():
    tracing.reset()
    yield
    tracing.reset()


class TestSpans:
    def test_span_records_duration_and_attrs(self):
        with tracing.span("work", cat="test", object="x") as sp:
            sp["outcome"] = "ok"
        (evt,) = tracing.snapshot()
        assert evt["name"] == "work" and evt["cat"] == "test"
        assert evt["ph"] == "X" and evt["dur"] >= 0
        assert evt["args"]["object"] == "x"
        assert evt["args"]["outcome"] == "ok"

    def test_nesting_links_parent(self):
        with tracing.span("outer"):
            with tracing.span("inner"):
                pass
        inner, outer = tracing.snapshot()  # inner closes first
        assert inner["name"] == "inner"
        assert inner["args"]["parent_span"] == outer["id"]
        assert "parent_span" not in outer["args"]

    def test_exception_recorded_and_reraised(self):
        with pytest.raises(ValueError):
            with tracing.span("boom"):
                raise ValueError("nope")
        (evt,) = tracing.snapshot()
        assert "ValueError" in evt["args"]["error"]

    def test_ring_is_bounded(self):
        tracing.configure(100)
        try:
            for i in range(250):
                with tracing.span(f"s{i}"):
                    pass
            events = tracing.snapshot()
            assert len(events) == 100
            assert events[-1]["name"] == "s249"  # newest kept, oldest gone
        finally:
            tracing.configure(10_000)

    def test_threads_do_not_cross_link(self):
        done = threading.Event()

        def other():
            with tracing.span("other-thread"):
                done.wait(2)

        t = threading.Thread(target=other)
        with tracing.span("main-thread"):
            t.start()
            done.set()
            t.join()
        by_name = {e["name"]: e for e in tracing.snapshot()}
        assert "parent_span" not in by_name["other-thread"]["args"]

    def test_chrome_export_shape(self):
        with tracing.span("a"):
            pass
        doc = json.loads(tracing.export_chrome())
        assert doc["traceEvents"][0]["ph"] == "X"

    def test_summarize(self):
        for _ in range(3):
            with tracing.span("repeat", cat="c1"):
                pass
        s = tracing.summarize(cat="c1")
        assert s["repeat"]["count"] == 3
        assert s["repeat"]["total_ms"] >= s["repeat"]["max_ms"]


class TestWiring:
    def test_fabric_wrapper_spans_every_verb(self):
        pool = TracedFabricProvider(InMemoryPool())
        pool.reserve_slice("s", "tpu-v4", "1x2x2", ["n0"])
        pool.get_resources()
        pool.release_slice("s")
        names = [e["name"] for e in tracing.snapshot()]
        assert names == [
            "fabric.reserve_slice", "fabric.get_resources",
            "fabric.release_slice",
        ]
        assert all(
            e["args"]["provider"] == "InMemoryPool" for e in tracing.snapshot()
        )

    def test_fabric_wrapper_caches_traced_verbs(self):
        """Verb wrappers are built once per instance — repeat access is a
        plain __dict__ hit (no closure rebuild on the attach hot path) and
        still records spans; non-verb instrumentation stays a live read."""
        pool = TracedFabricProvider(InMemoryPool())
        first = pool.get_resources
        assert pool.get_resources is first  # cached, not rebuilt
        assert "get_resources" in pool.__dict__
        first()
        first()
        names = [e["name"] for e in tracing.snapshot()]
        assert names.count("fabric.get_resources") == 2
        assert "free_chips" not in pool.__dict__  # passthrough not cached
        assert pool.free_chips("tpu-v4") == pool._inner.free_chips("tpu-v4")

    def test_reconcile_spans_nest_fabric_calls_and_serve_over_http(self):
        store = Store()
        n = Node(metadata=ObjectMeta(name="worker-0"))
        n.status.tpu_slots = 4
        store.create(n)
        pool = TracedFabricProvider(InMemoryPool())
        mgr = Manager(store=store, health_addr="127.0.0.1:0")
        mgr.add_controller(ComposabilityRequestReconciler(
            store, pool, timing=RequestTiming(updating_poll=0.02)))
        mgr.add_controller(ComposableResourceReconciler(
            store, pool, FakeNodeAgent(pool=pool._inner),
            timing=ResourceTiming(attach_poll=0.02, visibility_poll=0.02,
                                  detach_poll=0.02, detach_fast=0.02,
                                  busy_poll=0.02)))
        mgr.start()
        try:
            store.create(ComposabilityRequest(
                metadata=ObjectMeta(name="traced"),
                spec=ComposabilityRequestSpec(resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4)),
            ))
            import time
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if store.get(ComposabilityRequest, "traced").status.state == "Running":
                    break
                time.sleep(0.01)
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mgr.health_port}/debug/traces").read())
            events = doc["traceEvents"]
            recs = [e for e in events if e["name"] == "reconcile"]
            fabs = [e for e in events if e["name"].startswith("fabric.")]
            assert recs and fabs
            # A fabric call made inside a reconcile carries that span as
            # its parent — the nesting that makes the trace readable.
            rec_ids = {e["id"] for e in recs}
            assert any(f["args"].get("parent_span") in rec_ids for f in fabs)
            summary = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{mgr.health_port}/debug/traces/summary"
            ).read())
            assert summary["reconcile"]["count"] >= 1
        finally:
            mgr.stop()

    def test_trace_file_written_on_stop(self, tmp_path, monkeypatch):
        path = tmp_path / "trace.json"
        monkeypatch.setenv("TPUC_TRACE_FILE", str(path))
        mgr = Manager(store=Store())
        mgr.start()
        with tracing.span("before-stop"):
            pass
        mgr.stop()
        doc = json.loads(path.read_text())
        assert any(e["name"] == "before-stop" for e in doc["traceEvents"])
