"""Full train step across the 5-axis parallelism matrix (dp/ep/pp/sp/tp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_composer.models import MoEConfig, ModelConfig
from tpu_composer.parallel import (
    TrainConfig,
    make_mesh,
    make_train_state,
    make_train_step,
    solve_mesh_axes,
)


def dense_cfg(**kw):
    d = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
             max_seq=64, dtype=jnp.float32)
    d.update(kw)
    return ModelConfig(**d)


def moe_cfg(**kw):
    d = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
             max_seq=64, dtype=jnp.float32, n_experts=4, top_k=2,
             capacity_factor=2.0, moe_period=2)
    d.update(kw)
    return MoEConfig(**d)


def run_steps(tc, mesh, batch=4, seq=64, n=2):
    state = make_train_state(tc, jax.random.key(0), mesh)
    step_fn, batch_sharding = make_train_step(tc, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch, seq), 0,
                           tc.model.vocab_size),
        batch_sharding,
    )
    losses = []
    for _ in range(n):
        state, metrics = step_fn(state, tokens)
        losses.append(float(metrics["loss"]))
    return losses


def test_moe_step_on_dp_ep_tp_mesh():
    mesh = make_mesh(solve_mesh_axes(8, dp=2, ep=2, sp=1, tp=2))
    assert mesh.axis_names == ("dp", "ep", "sp", "tp")
    losses = run_steps(TrainConfig(model=moe_cfg()), mesh)
    assert np.isfinite(losses).all()
    assert losses[1] < losses[0]


def test_moe_step_with_sequence_parallel_ulysses():
    mesh = make_mesh(solve_mesh_axes(8, dp=1, ep=2, sp=2, tp=2))
    losses = run_steps(
        TrainConfig(model=moe_cfg(), sp_impl="ulysses"), mesh
    )
    assert np.isfinite(losses).all()


def test_pipelined_step_matches_unpipelined_first_loss():
    tokens_cfg = dense_cfg()
    mesh_pp = make_mesh(solve_mesh_axes(8, dp=2, pp=2, sp=1, tp=2))
    mesh_flat = make_mesh(solve_mesh_axes(8, dp=2, sp=2, tp=2))
    l_pp = run_steps(
        TrainConfig(model=tokens_cfg, pipeline_microbatches=2), mesh_pp, n=2
    )
    l_flat = run_steps(TrainConfig(model=tokens_cfg), mesh_flat, n=2)
    # Same init/key/data => identical first loss regardless of schedule.
    assert abs(l_pp[0] - l_flat[0]) < 1e-4
    assert l_pp[1] < l_pp[0]


def test_pipeline_with_sequence_parallel_nested():
    """'sp'-manual attention nested inside the 'pp'-manual GPipe stage."""
    mesh = make_mesh(solve_mesh_axes(8, dp=1, pp=2, sp=2, tp=2))
    losses = run_steps(
        TrainConfig(model=dense_cfg(), pipeline_microbatches=2), mesh
    )
    assert np.isfinite(losses).all()
    assert losses[1] < losses[0]


def test_all_sp_impls_match_ring_loss():
    """Every sequence-parallel strategy computes the same attention: first
    losses must agree bit-for-bit-ish across ring, zigzag and ulysses."""
    mesh = make_mesh(solve_mesh_axes(8, dp=2, sp=2, tp=2))
    l_ring = run_steps(TrainConfig(model=dense_cfg(), sp_impl="ring"), mesh, n=1)
    for impl in ("zigzag", "ulysses"):
        l_other = run_steps(
            TrainConfig(model=dense_cfg(), sp_impl=impl), mesh, n=1
        )
        assert abs(l_ring[0] - l_other[0]) < 1e-4, impl


def test_moe_with_pipeline_rejected():
    mesh = make_mesh(solve_mesh_axes(8, pp=2, sp=1, tp=2))
    with pytest.raises(ValueError, match="dense model only"):
        make_train_state(
            TrainConfig(model=moe_cfg(), pipeline_microbatches=2),
            jax.random.key(0), mesh,
        )


def test_bad_sp_impl_rejected():
    mesh = make_mesh(solve_mesh_axes(8, dp=2, sp=2, tp=2))
    with pytest.raises(ValueError, match="sp_impl"):
        make_train_step(TrainConfig(model=dense_cfg(), sp_impl="rings"), mesh)
