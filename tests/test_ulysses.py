"""Ulysses all-to-all sequence parallelism vs full attention."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from tpu_composer.ops.attention import flash_attention, mha_reference
from tpu_composer.parallel.ulysses import ulysses_attention


def qkv(b=2, s=32, h=8, d=16, key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_reference(causal):
    q, k, v = qkv()
    want = mha_reference(q, k, v, causal=causal)

    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]).reshape(4), ("sp",))
    spec = P(None, "sp", None, None)
    got = jax.jit(
        shard_map(
            functools.partial(ulysses_attention, axis_name="sp", causal=causal),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_matches_ring_attention():
    from tpu_composer.parallel.ring_attention import ring_attention

    q, k, v = qkv(key=1)
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]).reshape(4), ("sp",))
    spec = P(None, "sp", None, None)

    def run(fn):
        return jax.jit(
            shard_map(
                functools.partial(fn, axis_name="sp", causal=True),
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=False,
            )
        )(q, k, v)

    np.testing.assert_allclose(
        np.asarray(run(ulysses_attention)),
        np.asarray(run(ring_attention)),
        atol=1e-5,
    )


def test_flash_kernel_inside_ulysses():
    """The Pallas flash kernel is a drop-in local attention for Ulysses."""
    q, k, v = qkv(s=64, key=2)
    want = mha_reference(q, k, v, causal=True)
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:2]).reshape(2), ("sp",))
    spec = P(None, "sp", None, None)
    got = jax.jit(
        shard_map(
            functools.partial(
                ulysses_attention, axis_name="sp", causal=True,
                attn_fn=functools.partial(flash_attention, block_q=32, block_k=32),
            ),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_head_divisibility_error():
    q, k, v = qkv(h=6)
    devs = jax.devices()
    mesh = Mesh(np.array(devs[:4]).reshape(4), ("sp",))
    spec = P(None, "sp", None, None)
    with pytest.raises(ValueError, match="not divisible"):
        shard_map(
            functools.partial(ulysses_attention, axis_name="sp"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )(q, k, v)
