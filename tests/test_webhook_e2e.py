"""Admission webhooks in the wire loop: apiserver -> TLS webhook -> verdict.

VERDICT r2 weak #8: the AdmissionReview server was only ever tested against
itself; the fake apiserver never called out to it, so the TLS + review
round-trip the reference exercises in envtest (WebhookInstallOptions,
/root/reference/internal/webhook/v1alpha1/webhook_suite_test.go:74-144) had
no end-to-end coverage here. These tests register the REAL AdmissionServer
(self-signed TLS) with the fake apiserver exactly as a
ValidatingWebhookConfiguration/MutatingWebhookConfiguration would: every
create/update POSTs an AdmissionReview over HTTPS, denials fail the API
call, and JSONPatches land in the stored object.
"""

from __future__ import annotations

import json
import subprocess
import urllib.error
import urllib.request

import pytest

from tpu_composer import GROUP, VERSION
from tpu_composer.admission.coordinates import LABEL_INJECT, LABEL_WORKER_ID
from tpu_composer.admission.server import (
    AdmissionServer,
    MUTATE_PATH,
    VALIDATE_PATH,
)
from tpu_composer.api import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ObjectMeta,
    ResourceDetails,
)
from tpu_composer.api.types import SliceStatus
from tpu_composer.runtime.store import Store

from tests.fake_apiserver import FakeApiServer

CR_PREFIX = f"/apis/{GROUP}/{VERSION}/composabilityrequests"
POD_PREFIX = "/api/v1/pods"


@pytest.fixture(scope="module")
def tls_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("webhook-tls")
    cert, key = d / "tls.crt", d / "tls.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        check=True, capture_output=True,
    )
    return str(cert), str(key)


@pytest.fixture()
def world(tls_files):
    """Store + real TLS AdmissionServer + fake apiserver wired together."""
    cert, key = tls_files
    store = Store()
    webhook = AdmissionServer(store, bind="127.0.0.1:0",
                              certfile=cert, keyfile=key)
    webhook.start()
    base = f"https://{webhook.address}"
    srv = FakeApiServer(
        {
            CR_PREFIX: {"kind": "ComposabilityRequest",
                        "apiVersion": f"{GROUP}/{VERSION}"},
            POD_PREFIX: {"kind": "Pod", "apiVersion": "v1"},
        }
    )
    srv.webhooks = [
        {"prefix": CR_PREFIX, "url": base + VALIDATE_PATH,
         "operations": {"CREATE", "UPDATE"}},
        {"prefix": POD_PREFIX, "url": base + MUTATE_PATH,
         "operations": {"CREATE"}},
    ]
    srv.start()
    yield store, webhook, srv
    srv.stop()
    webhook.stop()


def api_post(srv, prefix, obj):
    req = urllib.request.Request(
        f"{srv.url}{prefix}", data=json.dumps(obj).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req).read())


def cr_doc(name, **res):
    spec = {"type": "tpu", "model": "tpu-v4", "size": 4}
    spec.update(res)
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "ComposabilityRequest",
        "metadata": {"name": name},
        "spec": {"resource": spec},
    }


class TestValidatingOverTheWire:
    def test_valid_request_admitted_and_stored(self, world):
        store, webhook, srv = world
        out = api_post(srv, CR_PREFIX, cr_doc("ok"))
        assert out["metadata"]["uid"]
        assert srv.get_object(CR_PREFIX, "ok") is not None

    def test_invalid_request_rejected_with_denial_message(self, world):
        store, webhook, srv = world
        bad = cr_doc("bad", allocation_policy="differentnode",
                     target_node="worker-0")
        with pytest.raises(urllib.error.HTTPError) as exc:
            api_post(srv, CR_PREFIX, bad)
        assert exc.value.code == 403
        body = json.loads(exc.value.read())
        # The denial carries the webhook's rule text, not a generic error.
        assert "differentnode" in body["message"]
        assert srv.get_object(CR_PREFIX, "bad") is None

    def test_duplicate_policy_rejected_via_store(self, world):
        store, webhook, srv = world
        # The webhook validates duplicates against ITS store view — seed one.
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="existing"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(
                    type="tpu", model="tpu-v4", size=4,
                    allocation_policy="differentnode",
                )
            ),
        ))
        with pytest.raises(urllib.error.HTTPError) as exc:
            api_post(
                srv, CR_PREFIX,
                cr_doc("dup", allocation_policy="differentnode"),
            )
        assert exc.value.code == 403


class TestSamenodeEffectiveTargetOverTheWire:
    def test_unpinned_incoming_with_allocated_node_denied(self, world):
        """The incoming request's node resolves via status when its spec
        has no target (VERDICT r3 missing #5), and the denial travels the
        full apiserver -> TLS webhook -> 403 wire path."""
        from tpu_composer.api.types import ResourceStatus

        store, webhook, srv = world
        store.create(ComposabilityRequest(
            metadata=ObjectMeta(name="pinned"),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="gpu", model="gpu-a100", size=1,
                allocation_policy="samenode", target_node="worker-3"))))
        unpinned = ComposabilityRequest(
            metadata=ObjectMeta(name="unpinned"),
            spec=ComposabilityRequestSpec(resource=ResourceDetails(
                type="gpu", model="gpu-a100", size=1,
                allocation_policy="samenode")))
        unpinned.status.resources["gpu-y"] = ResourceStatus(
            state="Online", node_name="worker-3")
        doc = unpinned.to_dict()
        with pytest.raises(urllib.error.HTTPError) as exc:
            api_post(srv, CR_PREFIX, doc)
        assert exc.value.code == 403
        assert "already targets worker-3" in json.loads(exc.value.read())["message"]


class TestMutatingOverTheWire:
    def test_tpu_pod_gets_coordinates_injected(self, world):
        store, webhook, srv = world
        req = ComposabilityRequest(
            metadata=ObjectMeta(name="train"),
            spec=ComposabilityRequestSpec(
                resource=ResourceDetails(type="tpu", model="tpu-v5e", size=8)
            ),
        )
        req.status.slice = SliceStatus(
            name="train-slice", topology="2x4", num_hosts=1,
            chips_per_host=8, worker_hostnames=["host-a"],
        )
        store.create(req)

        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "worker-0",
                "labels": {LABEL_INJECT: "train", LABEL_WORKER_ID: "0"},
            },
            "spec": {"containers": [{"name": "main", "image": "jax:latest"}]},
        }
        api_post(srv, POD_PREFIX, pod)
        stored = srv.get_object(POD_PREFIX, "worker-0")
        env = {e["name"]: e["value"]
               for e in stored["spec"]["containers"][0].get("env", [])}
        assert env.get("TPU_WORKER_ID") == "0"
        assert env.get("TPU_WORKER_HOSTNAMES") == "host-a"
        assert "2x4" in json.dumps(env)

    def test_unlabeled_pod_stored_untouched(self, world):
        store, webhook, srv = world
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "plain"},
            "spec": {"containers": [{"name": "main", "image": "busybox"}]},
        }
        api_post(srv, POD_PREFIX, pod)
        stored = srv.get_object(POD_PREFIX, "plain")
        assert "env" not in stored["spec"]["containers"][0]
