"""Wire plane v2 — the tpuc-mux/1 framed transport (ISSUE 19).

Three layers under test:

- the frame codec itself (length-prefixed JSON, partial reads dribbled
  across frame boundaries, truncation, the corrupt-length cap);
- one live socket doing everything at once against the sim apiserver:
  pipelined verbs, CAS conflicts, watch pushes interleaved with responses,
  mid-watch reconnect with a resume cursor, the 410-expired persona;
- the kill switch: ``wire_mux=False`` / ``TPUC_WIRE_MUX=0`` must run the
  PR 17 keep-alive HTTP path with byte-identical store semantics, and a
  server that declines the upgrade must demote the client to HTTP for
  good (``tpuc_wire_mux_active`` 0) without a single failed store op.

Plus the event-driven control loops the mux enables (part c of the
tentpole): UpstreamSyncer's relist demotion + inventory doorbell, and the
InventoryPublisher's event-fed ResourceSlice drift repair.
"""

from __future__ import annotations

import io
import json
import random
import shutil
import socket
import ssl
import subprocess
import threading
import time

import pytest

from tpu_composer import GROUP, VERSION
from tpu_composer.api.types import (
    ComposableResource,
    ComposableResourceSpec,
    ComposableResourceStatus,
    ObjectMeta,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.runtime import wiremux
from tpu_composer.runtime.kubestore import KubeConfig, KubeStore
from tpu_composer.runtime.metrics import (
    wire_mux_active,
    wire_mux_degraded_total,
    wire_mux_reconnects_total,
    wire_ping_rtt_seconds,
)
from tpu_composer.runtime.store import ConflictError, NotFoundError, StoreError
from tpu_composer.sim.netchaos import ChaosProxy

from tests.fake_apiserver import FakeApiServer, operator_resources

CR_PREFIX = f"/apis/{GROUP}/{VERSION}/composabilityrequests"
RES_PREFIX = f"/apis/{GROUP}/{VERSION}/composableresources"


def cr_doc(name: str, count: int = 0) -> dict:
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "ComposabilityRequest",
        "metadata": {"name": name},
        "spec": {"resource": {"type": "tpu", "model": "tpu-v4", "size": 1},
                 "count": count},
    }


# ----------------------------------------------------------------------
# frame codec
# ----------------------------------------------------------------------
class _Dribble:
    """File-like that returns at most ``chunk`` bytes per read — the
    pathological TCP segmentation the codec must ride out."""

    def __init__(self, data: bytes, chunk: int = 1) -> None:
        self._fp = io.BytesIO(data)
        self._chunk = chunk

    def read(self, n: int) -> bytes:
        return self._fp.read(min(n, self._chunk))


class TestFrameCodec:
    def test_roundtrip_one_byte_reads_across_frame_boundaries(self):
        frames = [
            {"id": 1, "method": "GET", "path": "/x", "body": None},
            {"watch": 2, "event": {"type": "ADDED", "object": {"a": "b" * 300}}},
            {"id": 3, "code": 409, "body": {"reason": "Conflict"}},
        ]
        wire = b"".join(wiremux.encode_frame(f) for f in frames)
        fp = _Dribble(wire, chunk=1)
        assert [wiremux.read_frame(fp) for _ in frames] == frames
        # Clean EOF exactly at a frame boundary: None, not an error.
        assert wiremux.read_frame(fp) is None

    def test_eof_mid_payload_is_a_truncation_error(self):
        wire = wiremux.encode_frame({"id": 1, "code": 200, "body": {}})
        fp = _Dribble(wire[:-3], chunk=5)
        with pytest.raises(wiremux.MuxError):
            wiremux.read_frame(fp)

    def test_eof_mid_length_prefix_is_a_truncation_error(self):
        wire = wiremux.encode_frame({"id": 1})
        with pytest.raises(wiremux.MuxError):
            wiremux.read_frame(_Dribble(wire[:2]))

    def test_eof_between_header_and_body(self):
        wire = wiremux.encode_frame({"id": 1})
        with pytest.raises(wiremux.MuxError):
            wiremux.read_frame(_Dribble(wire[:4], chunk=4))

    def test_corrupt_length_prefix_hits_the_cap(self):
        huge = (wiremux.MAX_FRAME + 1).to_bytes(4, "big") + b"x"
        with pytest.raises(wiremux.MuxError, match="cap"):
            wiremux.read_frame(_Dribble(huge, chunk=64))

    def test_garbage_payload_is_a_mux_error_not_a_leak(self):
        # Valid length prefix, non-JSON bytes: the codec owns the error
        # type — readers classify on MuxError, never raw ValueError.
        wire = len(b"\xff\xfe{not json").to_bytes(4, "big") + b"\xff\xfe{not json"
        with pytest.raises(wiremux.MuxError, match="corrupt frame payload"):
            wiremux.read_frame(_Dribble(wire, chunk=3))
        # Valid JSON that is not an object is just as dead on arrival.
        wire = len(b"[1,2]").to_bytes(4, "big") + b"[1,2]"
        with pytest.raises(wiremux.MuxError, match="not an object"):
            wiremux.read_frame(_Dribble(wire, chunk=5))


class TestFrameCodecFuzz:
    """Satellite: seeded codec fuzz. Whatever bytes arrive — valid frames
    chopped at random points, corrupt/oversized length prefixes, garbage
    payloads, truncations — the reader must return frames, return None
    (clean EOF), or raise MuxError. It must never hang and never try to
    allocate past the 64MB cap."""

    SEED = 0x7C20  # PR 20: reproducible corpus

    def _wire(self, rng: random.Random) -> bytes:
        frames = []
        for _ in range(rng.randint(1, 3)):
            frames.append({
                "id": rng.randint(1, 1 << 30),
                "method": rng.choice(["GET", "POST", "PUT", "DELETE"]),
                "path": "/x/" + "p" * rng.randint(0, 200),
                "body": {"k": "v" * rng.randint(0, 500)},
            })
        return b"".join(wiremux.encode_frame(f) for f in frames)

    def _mutate(self, rng: random.Random, wire: bytes) -> bytes:
        mode = rng.randrange(5)
        if mode == 0:
            return wire  # pristine
        if mode == 1 and len(wire) > 1:
            return wire[: rng.randrange(1, len(wire))]  # truncate mid-stream
        if mode == 2:
            i = rng.randrange(len(wire))
            return wire[:i] + bytes([wire[i] ^ (1 << rng.randrange(8))]) \
                + wire[i + 1:]  # single bit flip (prefix or payload)
        if mode == 3:
            # Replace a length prefix with 4 random bytes — including the
            # gigabyte-range values the MAX_FRAME cap exists for.
            return rng.randbytes(4) + wire[4:]
        return wire + rng.randbytes(rng.randint(1, 64))  # trailing garbage

    def test_seeded_fuzz_terminates_with_frames_none_or_mux_error(self):
        rng = random.Random(self.SEED)
        outcomes = {"frames": 0, "eof": 0, "error": 0}
        for _ in range(250):
            data = self._mutate(rng, self._wire(rng))
            fp = _Dribble(data, chunk=rng.choice([1, 2, 3, 7, 64, 4096]))
            # Hard bound on reader iterations: a hang here would mean the
            # codec can spin/block on hostile input.
            for _ in range(16):
                try:
                    frame = wiremux.read_frame(fp)
                except wiremux.MuxError:
                    outcomes["error"] += 1
                    break
                except MemoryError as e:  # pragma: no cover - the cap failed
                    raise AssertionError(
                        "codec tried to allocate past the frame cap") from e
                if frame is None:
                    outcomes["eof"] += 1
                    break
                assert isinstance(frame, dict)
                outcomes["frames"] += 1
            else:
                raise AssertionError("reader never terminated on fuzz input")
        # The corpus must actually exercise all three outcomes.
        assert all(outcomes.values()), outcomes

    def test_oversized_prefix_never_reads_the_claimed_size(self):
        rng = random.Random(self.SEED + 1)

        class CountingFp:
            def __init__(self, data: bytes) -> None:
                self._fp = io.BytesIO(data)
                self.asked = 0

            def read(self, n: int) -> bytes:
                self.asked = max(self.asked, n)
                return self._fp.read(n)

        for _ in range(50):
            size = rng.randint(wiremux.MAX_FRAME + 1, 1 << 40)
            fp = CountingFp(size.to_bytes(5, "big")[-4:] + b"x" * 16)
            size32 = int.from_bytes(size.to_bytes(5, "big")[-4:], "big")
            if size32 <= wiremux.MAX_FRAME:
                continue  # truncated to 32 bits below the cap: fine input
            with pytest.raises(wiremux.MuxError, match="cap"):
                wiremux.read_frame(fp)
            # The cap must reject BEFORE any body read is attempted.
            assert fp.asked <= wiremux._LEN.size


# ----------------------------------------------------------------------
# one socket, everything at once
# ----------------------------------------------------------------------
@pytest.fixture()
def srv():
    server = FakeApiServer(operator_resources(GROUP, VERSION))
    server.start()
    yield server
    server.stop()


class TestMuxLiveSocket:
    def test_pipelined_verbs_and_cas_conflict(self, srv):
        client = wiremux.MuxClient(srv.url)
        try:
            code, created = client.request("POST", CR_PREFIX,
                                           body=cr_doc("mux-a"))
            assert code == 201
            rv = created["metadata"]["resourceVersion"]
            # Two writers race the same resourceVersion through one
            # socket: exactly one admitted, the loser gets the Status
            # body with the same code/reason the HTTP transport returns.
            winner = dict(created)
            winner["spec"] = dict(winner["spec"], count=1)
            code, _ = client.request("PUT", f"{CR_PREFIX}/mux-a", body=winner)
            assert code == 200
            code, status = client.request("PUT", f"{CR_PREFIX}/mux-a",
                                          body=winner)
            assert code == 409
            assert status.get("reason") == "Conflict"
            # The request log carries the same (method, path) strings the
            # HTTP transport logs — the persona/cache assertions elsewhere
            # key on exactly this.
            assert ("POST", CR_PREFIX) in srv.request_log
            assert ("PUT", f"{CR_PREFIX}/mux-a") in srv.request_log
            assert rv  # sanity: versioned like the HTTP path
        finally:
            client.close()

    def test_injected_latency_does_not_serialize_pipelined_verbs(self, srv):
        client = wiremux.MuxClient(srv.url)
        srv.latency_s = 0.1
        try:
            n = 6
            errs = []

            def post(i):
                try:
                    code, _ = client.request("POST", CR_PREFIX,
                                             body=cr_doc(f"pipe-{i}"))
                    assert code == 201
                except Exception as e:  # surfaced below
                    errs.append(e)

            t0 = time.perf_counter()
            threads = [threading.Thread(target=post, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            wall = time.perf_counter() - t0
            assert not errs, errs
            # Serialized: n * 0.1 = 0.6s. Pipelined across the server's
            # verb pool the sleeps overlap; generous margin for CI noise.
            assert wall < 0.45, (
                f"{n} verbs with 100ms injected latency took {wall:.2f}s on"
                " one mux socket — the server is serializing the stream"
            )
        finally:
            srv.latency_s = 0.0
            client.close()

    def test_watch_push_interleaved_with_verbs_on_one_socket(self, srv):
        client = wiremux.MuxClient(srv.url)
        try:
            watch = client.watch(f"{CR_PREFIX}?watch=true&resourceVersion=0",
                                 timeout=5)
            names = [f"inter-{i}" for i in range(6)]
            # Mutate THROUGH the same connection the watch rides on.
            for n in names:
                assert client.request("POST", CR_PREFIX,
                                      body=cr_doc(n))[0] == 201
            assert client.request(
                "DELETE", f"{CR_PREFIX}/{names[0]}")[0] == 200
            seen = []
            rvs = []
            for line in watch:
                ev = json.loads(line)
                seen.append((ev["type"], ev["object"]["metadata"]["name"]))
                rvs.append(int(ev["object"]["metadata"]["resourceVersion"]))
                if ev["type"] == "DELETED":
                    break
            assert seen == [("ADDED", n) for n in names] + \
                [("DELETED", names[0])]
            assert rvs == sorted(rvs), f"pushes reordered: {rvs}"
            watch.shutdown()
        finally:
            client.close()

    def test_mid_watch_reconnect_resumes_from_cursor(self, srv):
        client = wiremux.MuxClient(srv.url)
        try:
            srv.put_object(CR_PREFIX, cr_doc("resume-a"))
            watch = client.watch(f"{CR_PREFIX}?watch=true&resourceVersion=0",
                                 timeout=5)
            ev = json.loads(next(watch))
            assert ev["type"] == "ADDED"
            cursor = int(ev["object"]["metadata"]["resourceVersion"])
        finally:
            client.close()  # connection drop mid-watch

        srv.put_object(CR_PREFIX, cr_doc("resume-b"))
        client2 = wiremux.MuxClient(srv.url)
        try:
            watch2 = client2.watch(
                f"{CR_PREFIX}?watch=true&resourceVersion={cursor}", timeout=5)
            ev = json.loads(next(watch2))
            # Resume replays only what happened AFTER the cursor: the
            # missed create, never the already-consumed one.
            assert (ev["type"], ev["object"]["metadata"]["name"]) == (
                "ADDED", "resume-b")
            watch2.shutdown()
        finally:
            client2.close()

    def test_compacted_resume_cursor_gets_410_error_event(self, srv):
        client = wiremux.MuxClient(srv.url)
        try:
            for i in range(4):
                srv.put_object(CR_PREFIX, cr_doc(f"gone-{i}"))
            srv.compact()
            watch = client.watch(f"{CR_PREFIX}?watch=true&resourceVersion=1",
                                 timeout=5)
            ev = json.loads(next(watch))
            assert ev["type"] == "ERROR"
            assert ev["object"]["code"] == 410
            # The stream ends after the expiry event, like the HTTP path.
            with pytest.raises(StopIteration):
                next(watch)
        finally:
            client.close()

    def test_watch_open_denied_maps_to_http_error(self, srv):
        srv.fail_hooks.append(
            lambda method, path: (503, "ServiceUnavailable", "boom")
            if "watch=true" in path else None
        )
        client = wiremux.MuxClient(srv.url)
        try:
            with pytest.raises(wiremux.MuxHTTPError) as ei:
                client.watch(f"{CR_PREFIX}?watch=true&resourceVersion=0",
                             timeout=5)
            assert ei.value.code == 503
        finally:
            srv.fail_hooks.clear()
            client.close()


# ----------------------------------------------------------------------
# kill switch + fallback
# ----------------------------------------------------------------------
class TestKillSwitch:
    @pytest.mark.parametrize("mux", [True, False])
    def test_store_semantics_identical_both_transports(self, srv, mux):
        store = KubeStore(config=KubeConfig(host=srv.url), cache_reads=False,
                          wire_mux=mux)
        try:
            r = ComposableResource(
                metadata=ObjectMeta(name="ks-par"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
                status=ComposableResourceStatus(),
            )
            store.create(r)
            got = store.get(ComposableResource, "ks-par")
            assert got.spec.model == "tpu-v4"
            got.spec.target_node = "n1"
            store.update(got)
            # Stale write: same typed ConflictError on both transports.
            with pytest.raises(ConflictError):
                store.update(got)
            fresh = store.get(ComposableResource, "ks-par")
            assert fresh.spec.target_node == "n1"
            assert [x.name for x in store.list(ComposableResource)] == [
                "ks-par"]
            store.delete(ComposableResource, "ks-par")
            with pytest.raises(NotFoundError):
                store.get(ComposableResource, "ks-par")
            # Transport sanity: mux-on actually used the mux, mux-off
            # never even dialed it.
            assert (store._mux is not None) is mux
        finally:
            store.close()

    def test_env_kill_switch_disables_mux(self, srv, monkeypatch):
        monkeypatch.setenv("TPUC_WIRE_MUX", "0")
        store = KubeStore(config=KubeConfig(host=srv.url), cache_reads=False)
        try:
            store.create(ComposableResource(
                metadata=ObjectMeta(name="ks-env"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
            ))
            assert store.get(ComposableResource, "ks-env").name == "ks-env"
            assert store._mux is None
        finally:
            store.close()

    def test_server_decline_falls_back_to_http_for_good(self, srv,
                                                        monkeypatch):
        def declined(self):
            raise wiremux.MuxUnsupported("server declined mux upgrade")

        monkeypatch.setattr(wiremux.MuxClient, "_handshake", declined)
        store = KubeStore(config=KubeConfig(host=srv.url), cache_reads=False,
                          wire_mux=True)
        try:
            store.create(ComposableResource(
                metadata=ObjectMeta(name="ks-decl"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
            ))
            # The op itself succeeded over HTTP, the demotion is permanent
            # (no per-request re-probing), and the gauge says degraded.
            assert store.get(ComposableResource, "ks-decl").name == "ks-decl"
            assert store._mux_failed
            assert wire_mux_active.total() == 0.0
        finally:
            store.close()

    def test_watch_cache_runs_on_mux(self, srv):
        """Reflector list+watch over the mux: cached reads are wire-free
        and the watch keeps the cache fresh — the PR 3 cache contract,
        unchanged on the new transport."""
        store = KubeStore(config=KubeConfig(host=srv.url), cache_reads=True,
                          watch_reconnect_s=0.05, wire_mux=True)
        try:
            store.create(ComposableResource(
                metadata=ObjectMeta(name="wc-a"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
            ))
            assert store.get(ComposableResource, "wc-a").name == "wc-a"
            before = len(srv.request_log)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if store.get(ComposableResource, "wc-a") is not None:
                    break
                time.sleep(0.01)
            for _ in range(20):
                store.get(ComposableResource, "wc-a")
            # Every one of those reads was served from the watch-fed
            # cache: zero new wire requests.
            assert len(srv.request_log) == before
            # Out-of-band server-side write still becomes visible through
            # the mux watch stream.
            srv.put_object(RES_PREFIX, {
                "apiVersion": f"{GROUP}/{VERSION}",
                "kind": "ComposableResource",
                "metadata": {"name": "wc-b"},
                "spec": {"type": "tpu", "model": "tpu-v4",
                         "targetNode": "n1"},
            })
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if any(r.name == "wc-b"
                       for r in store.list(ComposableResource)):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError(
                    "server-side create never reached the mux-fed cache")
        finally:
            store.close()


# ----------------------------------------------------------------------
# event-driven control loops (tentpole part c)
# ----------------------------------------------------------------------
class _StubSession:
    """Just the registration surface the runnables wire into."""

    def __init__(self, healthy: bool = True) -> None:
        self._healthy = healthy
        self.event_handlers = []
        self.gap_handlers = []
        self.state_handlers = []

    def on_event(self, h):
        self.event_handlers.append(h)

    def on_gap(self, h):
        self.gap_handlers.append(h)

    def on_state(self, h):
        self.state_handlers.append(h)

    def healthy(self):
        return self._healthy

    def fire(self, evt):
        for h in self.event_handlers:
            h(evt)


class TestEventDrivenLoops:
    def test_syncer_relist_demotion_tracks_session_health(self):
        from tpu_composer.controllers.syncer import UpstreamSyncer
        from tpu_composer.runtime.store import Store

        session = _StubSession(healthy=True)
        syncer = UpstreamSyncer(Store(), fabric=None, period=2.0,
                                session=session, fallback_multiplier=20.0)
        assert syncer.effective_period() == 40.0
        session._healthy = False
        assert syncer.effective_period() == 2.0
        # No session at all: plain timed cadence, exactly as before.
        assert UpstreamSyncer(Store(), fabric=None,
                              period=2.0).effective_period() == 2.0

    def test_syncer_wakes_on_inventory_events_only(self):
        from tpu_composer.controllers.syncer import UpstreamSyncer
        from tpu_composer.fabric.events import (
            EVENT_HEALTH,
            EVENT_INVENTORY,
            FabricEvent,
        )
        from tpu_composer.runtime.store import Store

        session = _StubSession()
        syncer = UpstreamSyncer(Store(), fabric=None, period=60.0,
                                session=session)
        session.fire(FabricEvent(seq=1, type=EVENT_HEALTH))
        assert not syncer._wake.is_set()
        session.fire(FabricEvent(seq=2, type=EVENT_INVENTORY))
        assert syncer._wake.is_set()
        syncer._wake.clear()
        # Gap recovery also rings: a lossy stream must trigger a diff.
        for h in session.gap_handlers:
            h()
        assert syncer._wake.is_set()

    def test_doorbell_bursts_coalesce_to_base_period(self):
        """A churny fabric rings the inventory doorbell once per
        attach/detach; the loop must coalesce the burst to at most one
        relist per base period, never one relist per ring (which would
        cost MORE wire ops than the timed poll the event plane demoted).
        """
        from tpu_composer.controllers.syncer import UpstreamSyncer
        from tpu_composer.fabric.events import EVENT_INVENTORY, FabricEvent
        from tpu_composer.runtime.store import Store

        session = _StubSession(healthy=True)
        syncer = UpstreamSyncer(Store(), fabric=None, period=0.3,
                                session=session, fallback_multiplier=100.0)
        passes: list = []
        syncer.sync_once = lambda: passes.append(time.monotonic())  # type: ignore[method-assign]
        stop = threading.Event()
        t = threading.Thread(target=syncer, args=(stop,),
                             name="coalesce-syncer", daemon=True)
        t.start()
        # ~90 rings over ~3 periods.
        end = time.monotonic() + 0.9
        while time.monotonic() < end:
            session.fire(FabricEvent(seq=1, type=EVENT_INVENTORY))
            time.sleep(0.01)
        time.sleep(0.1)
        stop.set()
        syncer._wake.set()
        t.join(5.0)
        assert not t.is_alive()
        # First ring fires immediately (quiet floor), then one pass per
        # period: ~4 passes for ~90 rings. Count-based with headroom —
        # the hard claim is "nowhere near one pass per ring".
        assert 1 <= len(passes) <= 5, passes
        gaps = [b - a for a, b in zip(passes, passes[1:])]
        assert all(g >= 0.25 for g in gaps), gaps

    def test_inventory_publisher_repairs_vanished_publication(self):
        from tpu_composer.agent.publisher import (
            DevicePublisher,
            InventoryPublisher,
        )
        from tpu_composer.fabric.provider import FabricDevice
        from tpu_composer.runtime.store import Store

        store = Store()
        owner = ComposableResource(
            metadata=ObjectMeta(name="inv-owner"),
            spec=ComposableResourceSpec(
                type="tpu", model="tpu-v4", target_node="inv-node"),
        )
        owner.status.state = RESOURCE_STATE_ONLINE
        owner.status.device_ids = ["dev-0", "dev-1"]
        store.create(owner)

        class Fabric:
            def get_resources(self):
                return [
                    FabricDevice(device_id=f"dev-{i}", node="inv-node",
                                 model="tpu-v4", slice_name="g0",
                                 resource_name="inv-owner")
                    for i in range(2)
                ]

        pub = InventoryPublisher(store, Fabric(), period=60.0)
        # Nothing published yet: the whole group is invisible -> repaired.
        assert pub.reconcile_once() == 1
        assert pub.repairs == 1
        dp = DevicePublisher(store)
        assert not dp.devices_invisible("inv-node", ["dev-0", "dev-1"])
        # Second pass is a no-op: publication present, no drift.
        assert pub.reconcile_once() == 0

    def test_inventory_publisher_leaves_inflight_owners_alone(self):
        from tpu_composer.agent.publisher import (
            DevicePublisher,
            InventoryPublisher,
        )
        from tpu_composer.api.types import PendingOp
        from tpu_composer.fabric.provider import FabricDevice
        from tpu_composer.runtime.store import Store

        store = Store()
        owner = ComposableResource(
            metadata=ObjectMeta(name="inv-busy"),
            spec=ComposableResourceSpec(
                type="tpu", model="tpu-v4", target_node="inv-node"),
        )
        owner.status.state = RESOURCE_STATE_ONLINE
        owner.status.device_ids = ["dev-9"]
        owner.status.pending_op = PendingOp(verb="add", nonce="n1")
        store.create(owner)

        class Fabric:
            def get_resources(self):
                return [FabricDevice(device_id="dev-9", node="inv-node",
                                     model="tpu-v4", slice_name="g0",
                                     resource_name="inv-busy")]

        pub = InventoryPublisher(store, Fabric(), period=60.0)
        # A pending fabric op means the controller owns this publication;
        # repairing now would race its own _mutate_slice write.
        assert pub.reconcile_once() == 0
        assert DevicePublisher(store).devices_invisible("inv-node", ["dev-9"])


# ----------------------------------------------------------------------
# liveness: pings, send deadline, watch death, flap damping (ISSUE 20)
# ----------------------------------------------------------------------
@pytest.fixture()
def chaos(srv):
    import urllib.parse

    host = urllib.parse.urlsplit(srv.url)
    proxy = ChaosProxy(host.hostname or "127.0.0.1", host.port or 80)
    yield proxy
    proxy.stop()


class TestMuxLiveness:
    def test_silent_partition_fails_all_pendings_and_watches_at_once(
            self, srv, chaos):
        """The half-open stall: bytes vanish in both directions but every
        socket stays open. The ping deadline must fail EVERY pending verb
        and the watch together, within ~2x the ping period — never one by
        one via 30s per-request timeouts."""
        rtt_before = wire_ping_rtt_seconds.count()
        client = wiremux.MuxClient(chaos.url, ping_period=0.2, ping_misses=1,
                                   connect_timeout=2.0)
        try:
            assert client.request("POST", CR_PREFIX,
                                  body=cr_doc("live-a"))[0] == 201
            watch = client.watch(
                f"{CR_PREFIX}?watch=true&resourceVersion=0", timeout=30)
            ev = json.loads(next(watch))
            assert ev["type"] == "ADDED"
            # Let at least one healthy ping/pong round-trip land.
            deadline = time.monotonic() + 5
            while (wire_ping_rtt_seconds.count() == rtt_before
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert wire_ping_rtt_seconds.count() > rtt_before

            chaos.partition()
            fails: list = []

            def pending_get():
                t0 = time.monotonic()
                try:
                    client.request("GET", f"{CR_PREFIX}/live-a", timeout=30)
                    fails.append(("response?!", time.monotonic() - t0))
                except wiremux.MuxError:
                    fails.append(("muxerr", time.monotonic() - t0))

            def pending_watch():
                t0 = time.monotonic()
                try:
                    next(watch)
                    fails.append(("event?!", time.monotonic() - t0))
                except wiremux.MuxError:
                    fails.append(("muxerr", time.monotonic() - t0))
                except StopIteration:
                    fails.append(("clean-end?!", time.monotonic() - t0))

            threads = [threading.Thread(target=pending_get,
                                        name=f"live-get-{i}")
                       for i in range(4)]
            threads.append(threading.Thread(target=pending_watch,
                                            name="live-watch"))
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert not any(t.is_alive() for t in threads), \
                "a pending verb/watch outlived the liveness deadline"
            kinds = [k for k, _ in fails]
            assert kinds.count("muxerr") == 5, fails
            # ≤ 2x ping period nominal (0.4s); generous CI slack but far
            # below the 30s per-request baseline.
            times = [dt for _, dt in fails]
            assert max(times) < 5.0, fails
            # "At once": one _fail sweep, not a serial bleed-out.
            assert max(times) - min(times) < 1.0, fails
        finally:
            client.close()

    def test_dead_connection_reconnects_and_counts_the_metric(self, srv,
                                                              chaos):
        before = wire_mux_reconnects_total.total()
        client = wiremux.MuxClient(chaos.url, ping_period=0.1, ping_misses=1,
                                   connect_timeout=2.0)
        try:
            assert client.request("POST", CR_PREFIX,
                                  body=cr_doc("rc-a"))[0] == 201
            chaos.cut()
            # The very next call rides the retry-once path onto a fresh
            # connection. Depending on when the reader notices the RST the
            # failure is "never sent" (retries any verb) or in-flight
            # ambiguous (retries only idempotent verbs) — a GET is safe
            # either way, which is exactly how KubeStore classifies it.
            code, body = client.request("GET", f"{CR_PREFIX}/rc-a",
                                        timeout=10, idempotent=True)
            assert code == 200 and body["metadata"]["name"] == "rc-a"
            assert wire_mux_reconnects_total.total() == before + 1
            # The reconnected wire served frames: no failure streak.
            assert client.fail_streak == 0
        finally:
            client.close()

    def test_send_timeout_unwedges_a_stalled_peer(self, srv, chaos):
        """A peer that stops draining the socket (slow-loris / half-open)
        must fail the send after ``send_timeout`` — not wedge the calling
        controller thread inside a blocking sendall forever."""
        client = wiremux.MuxClient(chaos.url, ping_period=0.0,
                                   send_timeout=1.0, connect_timeout=2.0)
        try:
            assert client.request("POST", CR_PREFIX,
                                  body=cr_doc("stall-a"))[0] == 201
            chaos.partition("c2s")  # proxy stops reading: buffers back up
            big = cr_doc("stall-b")
            big["spec"]["blob"] = "x" * (16 * 1024 * 1024)
            t0 = time.monotonic()
            with pytest.raises(wiremux.MuxError):
                client.request("POST", CR_PREFIX, body=big, timeout=30)
            # Two send attempts (the retry redials) at ~1s each, plus
            # encode time — nowhere near a wedged-forever sendall.
            assert time.monotonic() - t0 < 15.0
        finally:
            client.close()

    def test_killed_connection_fails_watch_well_under_idle_period(
            self, srv, chaos):
        """Satellite: when the connection dies, MuxWatch consumers must
        end immediately with a DISTINGUISHABLE connection-death error —
        not a clean StopIteration, not a 30s idle timeout."""
        client = wiremux.MuxClient(chaos.url, ping_period=0.0,
                                   connect_timeout=2.0)
        try:
            watch = client.watch(
                f"{CR_PREFIX}?watch=true&resourceVersion=0", timeout=30)
            outcome: list = []

            def consume():
                t0 = time.monotonic()
                try:
                    next(watch)
                    outcome.append(("event?!", time.monotonic() - t0))
                except wiremux.MuxError as e:
                    outcome.append(("muxerr", time.monotonic() - t0, str(e)))
                except (StopIteration, OSError):
                    outcome.append(("wrong-type", time.monotonic() - t0))

            t = threading.Thread(target=consume, name="watch-death")
            t.start()
            time.sleep(0.1)
            t_cut = time.monotonic()
            chaos.cut()
            t.join(timeout=10)
            assert not t.is_alive()
            assert outcome and outcome[0][0] == "muxerr", outcome
            assert "connection died" in outcome[0][2]
            # Re-establish end to end, well under one idle period (30s).
            srv.put_object(CR_PREFIX, cr_doc("rewatch-a"))
            watch2 = client.watch(
                f"{CR_PREFIX}?watch=true&resourceVersion=0", timeout=10)
            ev = json.loads(next(watch2))
            assert ev["object"]["metadata"]["name"] == "rewatch-a"
            assert time.monotonic() - t_cut < 10.0
            watch2.shutdown()
        finally:
            client.close()


class TestFlapDamping:
    def test_mux_http_fallback_needs_k_consecutive_failures(self, srv,
                                                            monkeypatch):
        """The damper: K consecutive CONNECTION failures demote to HTTP —
        once, permanently, counted — never a per-request flap."""
        dials = {"n": 0}

        def blackhole(self):
            dials["n"] += 1
            raise wiremux.MuxError("dial blackhole")

        monkeypatch.setattr(wiremux.MuxClient, "_handshake", blackhole)
        degraded_before = wire_mux_degraded_total.total()
        store = KubeStore(config=KubeConfig(host=srv.url), cache_reads=False,
                          wire_mux=True, wire_mux_max_fails=3)
        try:
            deadline = time.monotonic() + 20
            while not store._mux_failed and time.monotonic() < deadline:
                try:
                    store.get(ComposableResource, "absent")
                except (StoreError, NotFoundError):
                    pass
                # Paced past the redial backoff so each loop can be a real
                # dial attempt, not a fail-fast.
                time.sleep(0.08)
            assert store._mux_failed, "damper never tripped"
            assert dials["n"] >= 3, "demoted before K real dial attempts"
            assert wire_mux_degraded_total.total() == degraded_before + 1
            assert wire_mux_active.total() == 0.0
            # Demoted store works over HTTP immediately.
            store.create(ComposableResource(
                metadata=ObjectMeta(name="damped"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
            ))
            assert store.get(ComposableResource, "damped").name == "damped"
        finally:
            store.close()

    def test_one_mid_flight_loss_on_healthy_wire_never_degrades(self, srv):
        """Even with the damper at its most trigger-happy (K=1), a request
        lost on a connection that HAS served frames is a per-request
        failure: streak stays 0 and the mux stays up."""
        import urllib.parse

        host = urllib.parse.urlsplit(srv.url)
        chaos = ChaosProxy(host.hostname or "127.0.0.1", host.port or 80)
        store = KubeStore(config=KubeConfig(host=chaos.url),
                          cache_reads=False, wire_mux=True,
                          wire_mux_max_fails=1, wire_ping_period=0.2,
                          wire_ping_misses=1)
        try:
            store.create(ComposableResource(
                metadata=ObjectMeta(name="flap-a"),
                spec=ComposableResourceSpec(
                    type="tpu", model="tpu-v4", target_node="n0"),
            ))
            srv.latency_s = 0.4
            got: list = []

            def read_through_cut():
                # GET is idempotent: the ambiguous mid-flight loss retries
                # onto a fresh connection and succeeds.
                got.append(store.get(ComposableResource, "flap-a").name)

            t = threading.Thread(target=read_through_cut, name="flap-get")
            t.start()
            time.sleep(0.15)
            chaos.cut()
            t.join(timeout=15)
            assert not t.is_alive()
            assert got == ["flap-a"]
            assert not store._mux_failed, \
                "a per-request loss flapped the transport"
        finally:
            srv.latency_s = 0.0
            store.close()
            chaos.stop()


class TestChurnDriverMux:
    def test_churn_driver_speaks_mux(self, srv):
        from tpu_composer.sim.churn import ChurnDriver

        # wire_mux forced on: this test pins the mux path itself, and must
        # keep doing so in the CI leg that sets TPUC_WIRE_MUX=0 globally.
        drv = ChurnDriver(srv.url, plan=None, group=GROUP, version=VERSION,
                          wire_mux=True)
        try:
            code, _ = drv._req("POST", CR_PREFIX, cr_doc("churn-mux"))
            assert code == 201
            assert drv._mux is not None  # actually on the framed transport
            code, body = drv._req("GET", f"{CR_PREFIX}/churn-mux", None)
            assert code == 200
            assert body["metadata"]["name"] == "churn-mux"
        finally:
            drv.close()


# ----------------------------------------------------------------------
# TLS wire (REVIEW: ssl.SSLSocket.send() rejects MSG_DONTWAIT)
# ----------------------------------------------------------------------
class _TlsMuxServer:
    """Minimal TLS-terminating tpuc-mux/1 endpoint: per-connection thread
    does the TLS handshake, answers the HTTP Upgrade with 101, then echoes
    verbs and answers pings — or, with ``stall=True``, goes dark after the
    101 (never reads again) to model a slow-loris TLS peer."""

    def __init__(self, certfile: str, keyfile: str, stall: bool = False):
        self._ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        self._ctx.load_cert_chain(certfile, keyfile)
        self._stall = stall
        self._lsock = socket.create_server(("127.0.0.1", 0))
        self.port = self._lsock.getsockname()[1]
        self.url = f"https://127.0.0.1:{self.port}"
        self._stop = threading.Event()
        self._conns: list = []
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="tls-mux-accept").start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                raw, _ = self._lsock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(raw,), daemon=True,
                             name="tls-mux-conn").start()

    def _serve(self, raw: socket.socket) -> None:
        try:
            sock = self._ctx.wrap_socket(raw, server_side=True)
        except (ssl.SSLError, OSError):
            raw.close()
            return
        self._conns.append(sock)
        try:
            head = b""
            while b"\r\n\r\n" not in head:
                b1 = sock.recv(1)
                if not b1:
                    return
                head += b1
            sock.sendall(b"HTTP/1.1 101 Switching Protocols\r\n"
                         b"Upgrade: tpuc-mux/1\r\nConnection: Upgrade\r\n\r\n")
            if self._stall:
                self._stop.wait()  # handshake done, then never read again
                return
            rfile = sock.makefile("rb")
            while True:
                frame = wiremux.read_frame(rfile)
                if frame is None:
                    return
                if "ping" in frame:
                    sock.sendall(wiremux.encode_frame({"pong": frame["ping"]}))
                elif "id" in frame:
                    body = frame.get("body") or {}
                    sock.sendall(wiremux.encode_frame({
                        "id": frame["id"], "code": 200,
                        "body": {"echo_bytes": len(json.dumps(body))},
                    }))
        except (wiremux.MuxError, OSError, ValueError):
            return
        finally:
            sock.close()

    def stop(self) -> None:
        self._stop.set()
        for s in [self._lsock] + self._conns:
            try:
                s.close()
            except OSError:
                pass


@pytest.fixture(scope="module")
def tls_cert(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = d / "cert.pem", d / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "2",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return str(cert), str(key)


class _FlagRejectingSock:
    """Delegates to a real socket but rejects flags on send() exactly the
    way ``ssl.SSLSocket`` does — while NOT being an SSLSocket, so
    ``_send_bytes`` takes the MSG_DONTWAIT path and must convert the
    ValueError instead of letting it escape unclassified."""

    def __init__(self, sock: socket.socket):
        self._sock = sock

    def send(self, data, flags: int = 0) -> int:
        if flags:
            raise ValueError("non-zero flags not allowed in calls to send()")
        return self._sock.send(data)

    def __getattr__(self, name):
        return getattr(self._sock, name)


@pytest.mark.skipif(shutil.which("openssl") is None,
                    reason="openssl CLI unavailable for cert generation")
class TestTlsMux:
    def test_post_handshake_verbs_and_pings_cross_a_tls_wire(self, tls_cert):
        """Regression: ``ssl.SSLSocket.send()`` raises ValueError for any
        non-zero flags, so the MSG_DONTWAIT write path crashed EVERY
        post-handshake send on an https base_url — verbs, pings, watch
        cancels — escaping the MuxError contract. TLS must ride the
        flagless chunked path instead."""
        server = _TlsMuxServer(*tls_cert)
        ctx = ssl.create_default_context(cafile=tls_cert[0])
        rtt_before = wire_ping_rtt_seconds.count()
        client = wiremux.MuxClient(server.url, ssl_context=ctx,
                                   ping_period=0.1, connect_timeout=5.0)
        try:
            # Body big enough that _send_bytes takes several TLS chunks.
            blob = "x" * (4 * wiremux.TLS_SEND_CHUNK)
            code, body = client.request("POST", "/echo", body={"blob": blob},
                                        timeout=10)
            assert code == 200
            assert body["echo_bytes"] > len(blob)
            # The pinger thread survives too: before the fix its first
            # ping died on the same ValueError, silently killing liveness.
            deadline = time.monotonic() + 5
            while (wire_ping_rtt_seconds.count() == rtt_before
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert wire_ping_rtt_seconds.count() > rtt_before
        finally:
            client.close()
            server.stop()

    def test_stalled_tls_peer_fails_send_within_deadline(self, tls_cert):
        """The send deadline must hold on the TLS path as well: a peer
        that handshakes then never reads fails the send as a MuxError in
        ~send_timeout per attempt — no wedge, no ValueError."""
        server = _TlsMuxServer(*tls_cert, stall=True)
        ctx = ssl.create_default_context(cafile=tls_cert[0])
        client = wiremux.MuxClient(server.url, ssl_context=ctx,
                                   ping_period=0.0, send_timeout=1.0,
                                   connect_timeout=5.0)
        try:
            big = cr_doc("tls-stall")
            big["spec"]["blob"] = "x" * (8 * 1024 * 1024)
            t0 = time.monotonic()
            with pytest.raises(wiremux.MuxError):
                client.request("POST", CR_PREFIX, body=big, timeout=30)
            # Two send attempts (the retry redials) at ~1s each plus TLS
            # and encode overhead — nowhere near a wedged-forever send.
            assert time.monotonic() - t0 < 15.0
        finally:
            client.close()
            server.stop()


class TestSendValueErrorSafetyNet:
    def test_flag_rejecting_socket_fails_as_muxerror_not_valueerror(self):
        a, b = socket.socketpair()
        conn = wiremux._MuxConn(_FlagRejectingSock(a))
        try:
            with pytest.raises(wiremux.MuxError):
                conn.send({"id": 1, "method": "GET", "path": "/x"})
            assert conn.dead.is_set()
        finally:
            conn.close()
            b.close()
