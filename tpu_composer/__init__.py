"""tpu-composer: a TPU-native composable-resource framework.

A from-scratch rebuild of the capabilities of CoHDI/composable-resource-operator
(reference: /root/reference, a Go/kubebuilder K8s operator that hot-attaches
fabric-composable NVIDIA GPUs to cluster nodes) with TPUs as the first-class
device type:

- ``ComposabilityRequest{deviceType: tpu, count: N}`` drives a pluggable
  fabric/pool provider to reserve chips and program the ICI mesh into a valid
  slice topology (reference analog: internal/cdi/* fabric clients).
- Per chip-group ``ComposableResource`` objects run the attach/online/detach
  lifecycle (reference analog: internal/controller/composableresource_controller.go).
- A node agent generates CDI specs exposing ``/dev/accel*`` + libtpu mounts and
  verifies chip visibility/load (reference analog: internal/utils/gpus.go, which
  shells nvidia-smi via pod-exec).
- Admission webhooks validate requests and inject ``TPU_WORKER_ID`` /
  ``TPU_WORKER_HOSTNAMES`` coordinates (reference analog:
  internal/webhook/v1alpha1, validation only).
- A JAX workload layer (``tpu_composer.workload``, ``tpu_composer.parallel``,
  ``tpu_composer.models``) consumes the injected coordinates and runs sharded
  compute (collectives, ring attention, train steps) on the composed slice —
  the piece the reference, which never touches model execution, lacks.

The control plane is an in-process, watchable, persistent object store with
controller-runtime-style reconcilers (``tpu_composer.runtime``); it can stand
alone (tests, benches, single-box deployments) and mirrors the Kubernetes
semantics the reference relies on (optimistic concurrency, status subresource,
finalizers, watches).
"""

__version__ = "0.1.0"

GROUP = "tpu.composer.dev"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
