from tpu_composer.cmd.main import main

raise SystemExit(main())
