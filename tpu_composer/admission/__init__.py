"""Admission: validating rules + TPU coordinate injection.

Reference analog: internal/webhook/v1alpha1 (validating-only webhook on
ComposabilityRequest create/update, composabilityrequest_webhook.go:36-49).
Ours adds what SURVEY.md §7 (M3) calls for and the reference lacks: a
*mutating* side that injects ``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES`` /
topology env so JAX workloads see a native slice, sourced from the
authoritative ``status.slice`` the allocator wrote (hard-part #4: admission
output must match allocation output).
"""

from tpu_composer.admission.validating import register_validating_webhooks
from tpu_composer.admission.coordinates import slice_env, inject_pod_env

__all__ = ["register_validating_webhooks", "slice_env", "inject_pod_env"]
