"""TPU coordinate injection — the mutating-admission side.

The reference has no analog: it never provisions distributed-runtime
coordinates (SURVEY.md §5 "distributed communication backend": injection of
TPU_WORKER_ID / TPU_WORKER_HOSTNAMES via a mutating webhook is listed as the
TPU addition). The coordinate contract is what ``jax.distributed`` +
libtpu read on a multi-host slice:

  TPU_WORKER_ID             this host's index in the slice (worker order)
  TPU_WORKER_HOSTNAMES      comma-separated host list in worker order
  TPU_CHIPS_PER_HOST_BOUNDS per-dimension chip grid on one host, "x,y,z"
                            (libtpu parses bounds, not a count — e.g. v4's
                            tray is "2,2,1")
  TPU_HOST_BOUNDS           per-dimension host grid of the slice, "x,y,z"
  TPU_TOPOLOGY              slice shape, e.g. "2x2x4"
  TPU_SLICE_NAME            stable slice identity
  TPU_ACCELERATOR_MODEL     generation (tpu-v4, ...)

``slice_env`` derives all of it from ``ComposabilityRequest.status.slice`` —
the allocator's authoritative record — so injected coordinates can never
drift from the real allocation even across re-allocations (SURVEY.md §7
hard-part #4). ``inject_pod_env`` applies them to a K8s Pod manifest dict for
the real-cluster mutating webhook deployment.
"""

from __future__ import annotations

from typing import Dict

from tpu_composer.api.types import SliceStatus
from tpu_composer.topology.slices import TPU_MODELS, TopologyError, _parse_dims

#: Pods opt in by carrying this label with the request name as value.
LABEL_INJECT = "tpu.composer.dev/composability-request"
#: Pod label naming which worker of the slice this pod is.
LABEL_WORKER_ID = "tpu.composer.dev/worker-id"


def _bounds(slice_status: SliceStatus, model: str):
    """(chip-grid-per-host, host-grid) as 'x,y,z' strings.

    host bounds = slice dims / host tray dims, elementwise; when the model is
    unknown or the slice is sub-host, fall back to a linear layout.
    """
    try:
        dims = list(_parse_dims(slice_status.topology))
    except TopologyError:
        dims = []
    m = TPU_MODELS.get(model)

    def linear():
        chip = [max(1, slice_status.chips_per_host), 1, 1]
        host = [max(1, slice_status.num_hosts), 1, 1]
        return ",".join(map(str, chip)), ",".join(map(str, host))

    if (
        m is None
        or not dims
        or len(dims) != len(m.host_dims)
        or slice_status.chips_per_host < m.chips_per_host
    ):
        return linear()
    # Orient the host tray onto the slice dims: pair sorted tray factors with
    # sorted dims (solver dims are canonicalized ascending; a user-pinned
    # permutation still divides or we fall back to linear bounds).
    order = sorted(range(len(dims)), key=lambda i: dims[i])
    tray_sorted = sorted(m.host_dims)
    chip = [1] * len(dims)
    host = [1] * len(dims)
    for idx, t in zip(order, tray_sorted):
        d = dims[idx]
        if d % t != 0:
            return linear()
        chip[idx] = t
        host[idx] = d // t
    return ",".join(map(str, chip)), ",".join(map(str, host))


def slice_env(slice_status: SliceStatus, worker_id: int, model: str = "") -> Dict[str, str]:
    chip_bounds, host_bounds = _bounds(slice_status, model)
    env = {
        "TPU_WORKER_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": ",".join(slice_status.worker_hostnames),
        "TPU_CHIPS_PER_HOST_BOUNDS": chip_bounds,
        "TPU_HOST_BOUNDS": host_bounds,
        "TPU_TOPOLOGY": slice_status.topology,
        "TPU_SLICE_NAME": slice_status.name,
    }
    if model:
        env["TPU_ACCELERATOR_MODEL"] = model
    return env


def inject_pod_env(pod: Dict, slice_status: SliceStatus, worker_id: int, model: str = "") -> Dict:
    """Mutate a Pod manifest (dict form): append TPU_* env to every container
    and pin the pod to its worker's host via nodeSelector. Returns the pod."""
    env = slice_env(slice_status, worker_id, model)
    spec = pod.setdefault("spec", {})
    for container in spec.setdefault("containers", []):
        existing = {e.get("name") for e in container.setdefault("env", [])}
        for k, v in sorted(env.items()):
            if k not in existing:
                container["env"].append({"name": k, "value": v})
    if 0 <= worker_id < len(slice_status.worker_hostnames):
        spec.setdefault("nodeSelector", {})[
            "kubernetes.io/hostname"
        ] = slice_status.worker_hostnames[worker_id]
    return pod
