"""AdmissionReview HTTP(S) server — the wire side of the admission chain.

The reference serves its validating webhook through controller-runtime's
webhook server on :9443 with cert-manager TLS (cmd/main.go:101-103,:196-201;
the test suite stands up the real server and waits for TLS readiness,
webhook_suite_test.go:74-144). This module is that server for our two
webhooks, speaking `admission.k8s.io/v1` AdmissionReview JSON:

- ``/validate-tpu-composer-dev-v1alpha1-composabilityrequest``
  (deploy/webhook.yaml ValidatingWebhookConfiguration): decodes the
  embedded ComposabilityRequest, runs the same ``validate_request`` rules
  the in-process hook enforces, answers allowed/denied.
- ``/mutate-v1-pod`` (MutatingWebhookConfiguration): for Pods labeled
  ``tpu.composer.dev/composability-request``, looks up the request's
  authoritative ``status.slice`` and returns a JSONPatch injecting the
  TPU_* coordinate env + node pin (coordinates.inject_pod_env). The slice
  block in status is the single source of truth, so the patch can never
  disagree with the allocation (SURVEY.md §7 hard-part #4).

TLS: pass cert/key paths (the cert-manager mounted secret) to serve HTTPS;
without them the server speaks plain HTTP (in-cluster test setups,
port-forward debugging).
"""

from __future__ import annotations

import base64
import copy
import json
import logging
import socket
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_composer.admission.coordinates import (
    LABEL_INJECT,
    LABEL_WORKER_ID,
    inject_pod_env,
)
from tpu_composer.admission.validating import AdmissionDenied, validate_request
from tpu_composer.api.scheme import default_scheme
from tpu_composer.api.types import ComposabilityRequest
from tpu_composer.runtime.store import Store

VALIDATE_PATH = "/validate-tpu-composer-dev-v1alpha1-composabilityrequest"
MUTATE_PATH = "/mutate-v1-pod"


def make_server_tls_context(certfile: str, keyfile: Optional[str]) -> ssl.SSLContext:
    """Server-side TLS context from a cert/key pair — shared by the
    admission webhook and the secure metrics endpoint so cert-handling
    fixes land in one place."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


class _TlsPerConnectionServer(ThreadingHTTPServer):
    """TLS handshakes happen per connection in the worker thread, never in
    the accept loop: wrapping the *listening* socket makes SSLSocket.accept
    run do_handshake in serve_forever's thread, so one client stalling
    mid-handshake (half-open connection, port scanner) would block every
    subsequent AdmissionReview — and with failurePolicy Fail that wedges
    all CR admission cluster-wide."""

    ssl_context: Optional[ssl.SSLContext] = None
    daemon_threads = True
    handshake_timeout = 10.0
    # Post-handshake read timeout: long enough for the API server's
    # keep-alive reuse, short enough that dead peers release threads.
    io_timeout = 65.0

    def finish_request(self, request, client_address):
        if self.ssl_context is not None:
            request.settimeout(self.handshake_timeout)
            try:
                request = self.ssl_context.wrap_socket(request, server_side=True)
            except (ssl.SSLError, OSError):
                try:
                    request.close()
                except OSError:
                    pass
                return
            # wrap_socket detached the original socket, so ThreadingMixIn's
            # shutdown_request (which still holds the pre-wrap object) can
            # never shut the wrapped SSLSocket down — do it here, and reset
            # the handshake timeout so idle keep-alive connections are not
            # killed after 10s.
            request.settimeout(self.io_timeout)
            try:
                super().finish_request(request, client_address)
            finally:
                try:
                    request.shutdown(socket.SHUT_WR)
                except OSError:
                    pass
                try:
                    request.close()
                except OSError:
                    pass
            return
        super().finish_request(request, client_address)

    def handle_error(self, request, client_address):
        """Expected disconnects (client closed mid-request, TLS teardown,
        idle timeout) are connection noise, not server errors — log at debug
        instead of dumping tracebacks to stderr."""
        import sys

        exc = sys.exception()
        if isinstance(exc, (ConnectionError, TimeoutError, ssl.SSLError, OSError)):
            logging.getLogger("AdmissionServer").debug(
                "connection from %s dropped: %s", client_address, exc
            )
            return
        super().handle_error(request, client_address)


def _review_response(uid: str, allowed: bool, message: str = "",
                     patch: Optional[list] = None) -> dict:
    response: dict = {"uid": uid, "allowed": allowed}
    if message:
        response["status"] = {"message": message}
    if patch is not None:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": response,
    }


class AdmissionServer:
    """Serves both webhooks for one Store."""

    def __init__(
        self,
        store: Store,
        bind: str = "127.0.0.1:0",
        certfile: Optional[str] = None,
        keyfile: Optional[str] = None,
    ) -> None:
        self.store = store
        self.log = logging.getLogger("AdmissionServer")
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):  # noqa: N802 — readiness for the Service probe
                if self.path == "/healthz":
                    return self._send(200, {"ok": True})
                self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length") or 0)
                try:
                    review = json.loads(self.rfile.read(length)) if length else {}
                except ValueError:
                    return self._send(400, {"error": "bad JSON body"})
                request = review.get("request") or {}
                uid = request.get("uid", "")
                if self.path == VALIDATE_PATH:
                    return self._send(200, server._validate(uid, request))
                if self.path == MUTATE_PATH:
                    return self._send(200, server._mutate(uid, request))
                self._send(404, {"error": f"no webhook at {self.path}"})

            def _send(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        host, _, port = bind.rpartition(":")
        # ":9443"-style binds (the deploy manifest form) listen on all
        # interfaces, like the manager's health server.
        self._httpd = _TlsPerConnectionServer(
            (host or ("0.0.0.0" if bind.startswith(":") else "127.0.0.1"),
             int(port)),
            Handler,
        )
        if certfile:
            self._httpd.ssl_context = make_server_tls_context(certfile, keyfile)
        self.tls = bool(certfile)
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _validate(self, uid: str, request: dict) -> dict:
        try:
            obj = default_scheme().decode(request.get("object") or {})
            if not isinstance(obj, ComposabilityRequest):
                raise AdmissionDenied(
                    f"unexpected kind {type(obj).__name__} at {VALIDATE_PATH}"
                )
            obj.spec.validate()
            validate_request(self.store, obj)
        except Exception as e:
            return _review_response(uid, False, str(e))
        return _review_response(uid, True)

    def _mutate(self, uid: str, request: dict) -> dict:
        pod = request.get("object") or {}
        labels = (pod.get("metadata") or {}).get("labels") or {}
        req_name = labels.get(LABEL_INJECT, "")
        if not req_name:
            return _review_response(uid, True)  # not opted in — no patch
        req = self.store.try_get(ComposabilityRequest, req_name)
        if req is None or not req.status.slice.name:
            # failurePolicy: Ignore — admit unpatched rather than block pods
            # racing the allocation; the workload will crash-loop and retry
            # until the slice is Running.
            return _review_response(
                uid, True,
                f"request {req_name!r} not found or slice not allocated yet",
            )
        try:
            worker_id = int(labels.get(LABEL_WORKER_ID, "0"))
        except ValueError:
            return _review_response(uid, False,
                                    f"bad {LABEL_WORKER_ID} label")
        patched = inject_pod_env(
            copy.deepcopy(pod), req.status.slice, worker_id,
            req.spec.resource.model,
        )
        patch = [{"op": "replace", "path": "/spec", "value": patched["spec"]}]
        return _review_response(uid, True, patch=patch)

    # ------------------------------------------------------------------
    @property
    def address(self) -> str:
        host, port = self._httpd.server_address
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="admission-webhook", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # Manager runnable form (mgr.add_runnable(server.run)).
    def run(self, stop_event: threading.Event) -> None:
        self.start()
        stop_event.wait()
        self.stop()
