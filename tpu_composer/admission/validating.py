"""Validating admission for ComposabilityRequest.

Rules mirror composabilityrequest_webhook.go:91-128:
1. ``target_node`` cannot be combined with ``allocation_policy:
   differentnode`` (:91-93);
2. two ``differentnode`` requests for the same (type, model) conflict — they
   would race for the same spread (:97-107);
3. two ``samenode`` requests for the same (type, model) pinned (explicitly or
   by allocation) to the same node conflict (:108-128).

Runs in-process through the store's admission chain; the same callable backs
the HTTP webhook endpoint when deployed against a real K8s API server.
"""

from __future__ import annotations

from typing import Optional

from tpu_composer.api.types import ComposabilityRequest, ValidationError
from tpu_composer.runtime.store import Store


class AdmissionDenied(ValidationError):
    pass


def _effective_target(req: ComposabilityRequest) -> str:
    """Explicit target_node, else the node the allocator already chose
    (the webhook's status fallback, :113-122)."""
    if req.spec.resource.target_node:
        return req.spec.resource.target_node
    for rs in req.status.resources.values():
        if rs.node_name:
            return rs.node_name
    return ""


def validate_request(store: Store, req: ComposabilityRequest) -> None:
    res = req.spec.resource

    if req.being_deleted:
        # Deletion-path updates (finalizer removal PUTs) must never be
        # denied: a conflict verdict here would wedge the object in
        # Deleting forever. The allocator likewise stops counting
        # terminating requests, so there is nothing left to protect.
        return

    if res.allocation_policy == "differentnode" and res.target_node:
        raise AdmissionDenied(
            "target_node cannot be specified when allocation_policy is 'differentnode'"
        )

    for other in store.list(ComposabilityRequest):
        if other.name == req.name or other.being_deleted:
            continue
        o = other.spec.resource
        if o.type != res.type or o.model != res.model:
            continue
        if res.allocation_policy == "differentnode":
            if o.allocation_policy == "differentnode":
                raise AdmissionDenied(
                    f"composabilityRequest {other.name} with type {res.type} and"
                    f" model {res.model} already exists"
                )
        elif res.allocation_policy == "samenode":
            # Deliberate deviation from composabilityrequest_webhook.go:
            # 108-128, which compares against the incoming SPEC target_node
            # only (so two unpinned never-allocated requests collide on
            # "" == "" and an allocated-unpinned update is checked at "").
            # Here BOTH sides resolve spec-then-status: an unpinned,
            # never-allocated request has no node yet — no conflict to
            # detect — while updates are checked at the node the request
            # actually occupies. Recorded in docs/PARITY.md row 15.
            mine = _effective_target(req)
            if mine and _effective_target(other) == mine:
                raise AdmissionDenied(
                    f"composabilityRequest {other.name} with type {res.type} and"
                    f" model {res.model} already targets {mine}"
                )


def validate_maintenance(store: Store, obj, old=None) -> None:
    """NodeMaintenance admission: schema validation, node_name
    immutability (retargeting a live drain would orphan the old node's
    cordon marker and evacuation marks — delete and recreate instead),
    and one-drain-per-node — two live drains for the same host would race
    each other's cordon marker and double-claim the same members."""
    if obj.being_deleted:
        return
    obj.validate()
    if old is not None and old.spec.node_name != obj.spec.node_name:
        raise AdmissionDenied(
            "spec.node_name is immutable: delete the NodeMaintenance"
            " (uncordoning the old node) and create a new one"
        )
    from tpu_composer.api.maintenance import NodeMaintenance

    for other in store.list(NodeMaintenance):
        if other.name == obj.name or other.being_deleted:
            continue
        if other.spec.node_name == obj.spec.node_name:
            raise AdmissionDenied(
                f"nodeMaintenance {other.name} already drains"
                f" {obj.spec.node_name}"
            )


def register_validating_webhooks(store: Store) -> None:
    """Hook the rules into create/update, like SetupWebhookWithManager
    (cmd/main.go:196-201)."""

    def hook(op: str, new, old) -> None:
        if op in ("CREATE", "UPDATE"):
            validate_request(store, new)

    store.register_admission("ComposabilityRequest", hook)

    def maint_hook(op: str, new, old) -> None:
        if op in ("CREATE", "UPDATE"):
            validate_maintenance(store, new, old=old)

    store.register_admission("NodeMaintenance", maint_hook)
