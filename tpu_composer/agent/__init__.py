"""Node/device plumbing: the TPU analog of internal/utils (gpus.go, nodes.go).

The reference actuates node device stacks by pod-exec'ing nvidia-smi /
modprobe / sysfs writes into privileged pods (gpus.go:1040-1067). The TPU
equivalent is a **node agent**: it owns ``/dev/accel*`` and ``/dev/vfio/*``
visibility, generates CDI (Container Device Interface) specs with libtpu
mounts, scans ``/proc`` for open device fds before drain, and quarantines
devices during detach.

Three implementations share the NodeAgent interface (the injectable seam the
reference lacked — it monkey-patched SPDY executors in tests, SURVEY.md §4
takeaway):
- LocalNodeAgent: real host operations (TPU VM), with a C++ fast path
  (native/tpunode.cc via ctypes) and a pure-Python fallback;
- FakeNodeAgent: in-memory world for tests/benches.
"""

from tpu_composer.agent.nodeagent import (
    AgentError,
    DeviceBusyError,
    DriverType,
    NodeAgent,
)
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.cdi import CdiSpec, generate_cdi_spec

__all__ = [
    "AgentError",
    "DeviceBusyError",
    "DriverType",
    "NodeAgent",
    "FakeNodeAgent",
    "CdiSpec",
    "generate_cdi_spec",
]
