"""CDI (Container Device Interface) spec generation for TPU chip groups.

Replaces the reference's NVIDIA device-stack refresh path: where the reference
restarts nvidia-device-plugin daemonsets or pokes DRA kubelet plugins so
containers see `/dev/nvidiaX` (composableresource_controller.go:252-286), a
composed TPU chip group is published to container runtimes as a CDI spec —
one JSON document per chip group exposing:

- the accel device nodes (``/dev/accel<N>``) or vfio nodes for the chips,
- the libtpu mount (``libtpu.so`` is how JAX/XLA drive the chip),
- the ``TPU_*`` coordinate env so a JAX process sees a native slice
  (BASELINE.json north star: "no GPU driver in the loop").

Spec layout follows the CDI 0.6 schema (cdi.k8s.io), so real container
runtimes (containerd/CRI-O with CDI enabled) can consume it unchanged.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

CDI_VERSION = "0.6.0"
CDI_VENDOR = "tpu.composer.dev"
CDI_CLASS = "tpu"
DEFAULT_CDI_DIR = "/var/run/cdi"
DEFAULT_LIBTPU_PATH = "/lib/libtpu.so"


@dataclass
class CdiSpec:
    """One chip-group's CDI document."""

    name: str  # device name within the vendor/class, e.g. "slice-req1-worker0"
    device_nodes: List[str]
    env: Dict[str, str] = field(default_factory=dict)
    libtpu_host_path: str = DEFAULT_LIBTPU_PATH

    @property
    def qualified_name(self) -> str:
        return f"{CDI_VENDOR}/{CDI_CLASS}={self.name}"

    def to_dict(self) -> Dict:
        return {
            "cdiVersion": CDI_VERSION,
            "kind": f"{CDI_VENDOR}/{CDI_CLASS}",
            "devices": [
                {
                    "name": self.name,
                    "containerEdits": {
                        "deviceNodes": [{"path": p} for p in self.device_nodes],
                        "mounts": [
                            {
                                "hostPath": self.libtpu_host_path,
                                "containerPath": DEFAULT_LIBTPU_PATH,
                                "options": ["ro", "nosuid", "nodev", "bind"],
                            }
                        ],
                        "env": [f"{k}={v}" for k, v in sorted(self.env.items())],
                    },
                }
            ],
        }


def generate_cdi_spec(
    slice_name: str,
    worker_id: int,
    chip_indices: List[int],
    env: Optional[Dict[str, str]] = None,
    use_vfio: bool = False,
) -> CdiSpec:
    """Build the spec for one worker's chip group.

    chip_indices are host-local accel indices (0..chips_per_host-1); with
    ``use_vfio`` the chips are exposed through vfio device nodes instead
    (IOMMU passthrough hosts).
    """
    if use_vfio:
        nodes = ["/dev/vfio/vfio"] + [f"/dev/vfio/{i}" for i in chip_indices]
    else:
        nodes = [f"/dev/accel{i}" for i in chip_indices]
    name = f"{slice_name}-worker{worker_id}" if slice_name else f"chips-{'-'.join(map(str, chip_indices))}"
    return CdiSpec(name=name, device_nodes=nodes, env=dict(env or {}))


def spec_path(cdi_dir: str, spec: CdiSpec) -> str:
    return os.path.join(cdi_dir, f"{CDI_VENDOR}-{CDI_CLASS}-{spec.name}.json")


def write_cdi_spec(cdi_dir: str, spec: CdiSpec) -> str:
    """Atomically write the spec document; returns its path."""
    os.makedirs(cdi_dir, exist_ok=True)
    path = spec_path(cdi_dir, spec)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(spec.to_dict(), f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def remove_cdi_spec(cdi_dir: str, name: str) -> bool:
    path = os.path.join(cdi_dir, f"{CDI_VENDOR}-{CDI_CLASS}-{name}.json")
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False


def list_cdi_specs(cdi_dir: str) -> List[str]:
    if not os.path.isdir(cdi_dir):
        return []
    prefix = f"{CDI_VENDOR}-{CDI_CLASS}-"
    return sorted(
        fn[len(prefix):-len(".json")]
        for fn in os.listdir(cdi_dir)
        if fn.startswith(prefix) and fn.endswith(".json")
    )
