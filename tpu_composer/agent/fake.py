"""FakeNodeAgent — the injectable node world for tests and benches.

Replaces the reference's gomonkey SPDY-executor interception
(composableresource_controller_test.go:2702-2713) with a real implementation
of the NodeAgent interface. Optionally wired to an InMemoryPool so chip
visibility follows fabric attachment the way real hosts behave (a chip
enumerates as /dev/accelN only after the fabric programs the link), plus
explicit knobs for every failure mode the reference's canned-output tests
cover: missing driver, delayed visibility, stuck loads, taint state.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from tpu_composer.agent.cdi import CdiSpec
from tpu_composer.agent.nodeagent import (
    AgentError,
    DeviceBusyError,
    DriverType,
    NodeAgent,
)


class FakeNodeAgent(NodeAgent):
    def __init__(self, pool=None, fabric=None, fabric_ttl_s: float = 0.05) -> None:
        self._pool = pool  # InMemoryPool or None
        # Wire-mode visibility: when the pool lives in another process
        # (proc-mode fleet, REST provider), chip enumeration follows the
        # fabric's own attachment listing via provider.get_resources().
        # A short TTL cache keeps visibility polls from hammering the
        # fabric service during wide attach waves.
        self._fabric = fabric  # FabricProvider or None
        self._fabric_ttl_s = fabric_ttl_s
        self._fabric_cache: Optional[Dict[str, Set[str]]] = None
        self._fabric_cache_at = 0.0
        self._lock = threading.RLock()
        self._drivers: Dict[str, str] = {}  # node -> DriverType (default HOST)
        self._no_driver: Set[str] = set()
        self._visible: Dict[str, Set[str]] = {}  # node -> device ids (pool-less mode)
        self._visibility_delay: Dict[str, int] = {}  # node -> polls until visible
        self._loads: Dict[str, Set[str]] = {}  # node -> busy device ids
        self._taints: Dict[str, str] = {}  # device id -> reason
        self._published: Dict[str, Dict[str, CdiSpec]] = {}  # node -> name -> spec
        self.drain_calls: List[tuple] = []
        # Detach-path failure personas (the reference's Detaching-tree
        # canned failures, composableresource_controller_test.go):
        self._linger: Dict[str, int] = {}  # node -> polls chips keep enumerating
        self._load_check_fails: Dict[str, int] = {}  # node -> raising polls
        self._taint_cleanup_fails: Dict[str, int] = {}  # node -> raising calls

    # ------------------------------------------------------------------
    # NodeAgent interface
    # ------------------------------------------------------------------
    def ensure_driver(self, node: str) -> str:
        with self._lock:
            if node in self._no_driver:
                raise AgentError(f"no libtpu on {node}")
            return self._drivers.get(node, DriverType.HOST)

    def check_visible(self, node: str, device_ids: List[str], group: str = "") -> bool:
        with self._lock:
            if self._linger.get(node, 0) > 0 and device_ids:
                # Fabric already released the chips but the host's device
                # nodes haven't dropped yet ("ResourceSlice is still
                # visible", reference :5533) — detach must loop, not finish.
                self._linger[node] -= 1
                return True
            delay = self._visibility_delay.get(node, 0)
            if delay > 0:
                self._visibility_delay[node] = delay - 1
                return False
            if self._pool is not None:
                attached = set(self._pool.attached_to(node))
            elif self._fabric is not None:
                attached = self._fabric_attached().get(node, set())
            else:
                attached = self._visible.get(node, set())
            return bool(device_ids) and set(device_ids) <= attached

    def _fabric_attached(self) -> Dict[str, Set[str]]:
        """node -> attached device ids, via the wire provider (TTL-cached).
        Caller holds self._lock."""
        import time as _time

        now = _time.monotonic()
        if (
            self._fabric_cache is None
            or now - self._fabric_cache_at >= self._fabric_ttl_s
        ):
            try:
                listing = self._fabric.get_resources()
            except Exception:
                if self._fabric_cache is not None:
                    return self._fabric_cache  # stale beats a crashed poll
                raise
            out: Dict[str, Set[str]] = {}
            for d in listing:
                out.setdefault(d.node, set()).add(d.device_id)
            self._fabric_cache = out
            self._fabric_cache_at = now
        return self._fabric_cache

    def check_no_loads(self, node: str, device_ids: List[str], group: str = "") -> bool:
        with self._lock:
            if self._load_check_fails.get(node, 0) > 0:
                # The probe itself failing (nvidia-smi erroring in the
                # reference, :4303) is an AgentError, not "busy".
                self._load_check_fails[node] -= 1
                raise AgentError(f"load probe failed on {node}")
            busy = self._loads.get(node, set())
            return not (busy & set(device_ids))

    def drain(self, node: str, device_ids: List[str], force: bool = False, group: str = "") -> None:
        with self._lock:
            self.drain_calls.append((node, tuple(device_ids), force))
            busy = self._loads.get(node, set()) & set(device_ids)
            if busy and not force:
                raise DeviceBusyError(f"{node}: open handles on {sorted(busy)}")
            if force:
                self._loads.get(node, set()).difference_update(device_ids)
            self._visible.get(node, set()).difference_update(device_ids)

    def refresh_device_stack(self, node, spec: Optional[CdiSpec] = None, remove_name: str = ""):
        with self._lock:
            pubs = self._published.setdefault(node, {})
            if spec is not None:
                pubs[spec.name] = spec
            if remove_name:
                pubs.pop(remove_name, None)

    def create_device_taint(self, node, device_ids, reason):
        with self._lock:
            for d in device_ids:
                self._taints[d] = reason

    def delete_device_taint(self, node, device_ids):
        with self._lock:
            if self._taint_cleanup_fails.get(node, 0) > 0:
                self._taint_cleanup_fails[node] -= 1
                raise AgentError(f"taint cleanup failed on {node}")
            for d in device_ids:
                self._taints.pop(d, None)

    def has_device_taint(self, node, device_id) -> bool:
        with self._lock:
            return device_id in self._taints

    # ------------------------------------------------------------------
    # test knobs
    # ------------------------------------------------------------------
    def set_lingering(self, node: str, polls: int) -> None:
        """Chips keep enumerating for N visibility polls after detach."""
        with self._lock:
            self._linger[node] = polls

    def fail_load_check(self, node: str, times: int = 1) -> None:
        with self._lock:
            self._load_check_fails[node] = times

    def fail_taint_cleanup(self, node: str, times: int = 1) -> None:
        with self._lock:
            self._taint_cleanup_fails[node] = times

    def set_no_driver(self, node: str, missing: bool = True) -> None:
        with self._lock:
            if missing:
                self._no_driver.add(node)
            else:
                self._no_driver.discard(node)

    def set_driver_type(self, node: str, driver: str) -> None:
        with self._lock:
            self._drivers[node] = driver

    def set_visible(self, node: str, device_ids: List[str]) -> None:
        """Pool-less mode: mark chips as enumerating on the host."""
        with self._lock:
            self._visible.setdefault(node, set()).update(device_ids)

    def set_visibility_delay(self, node: str, polls: int) -> None:
        """Chip shows up only after N visibility checks (slow PCIe rescan)."""
        with self._lock:
            self._visibility_delay[node] = polls

    def add_load(self, node: str, device_id: str) -> None:
        with self._lock:
            self._loads.setdefault(node, set()).add(device_id)

    def clear_loads(self, node: str) -> None:
        with self._lock:
            self._loads.pop(node, None)

    def published(self, node: str) -> List[str]:
        with self._lock:
            return sorted(self._published.get(node, {}))

    def published_spec(self, node: str, name: str) -> Optional[CdiSpec]:
        with self._lock:
            return self._published.get(node, {}).get(name)

    def taints(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._taints)
