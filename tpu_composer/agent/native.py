"""ctypes binding to the native node-agent core (native/tpunode.cc).

Loads ``libtpunode.so`` from (in order) $TPUNODE_LIB, the repo's
``native/build`` directory, or the system loader. Returns None when absent so
callers fall back to the pure-Python implementations with identical
semantics — the library is an optimization for the syscall-heavy polling
paths (full /proc fd sweeps each drain check), not a requirement.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

_lock = threading.Lock()
_loaded = False
_lib: Optional["_NativeLib"] = None


class _NativeLib:
    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._c = cdll
        self._c.tpun_version.restype = ctypes.c_char_p
        self._c.tpun_enum_accel.restype = ctypes.c_int
        self._c.tpun_enum_accel.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        self._c.tpun_fd_holders.restype = ctypes.c_int
        self._c.tpun_fd_holders.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        self._c.tpun_read_file.restype = ctypes.c_int
        self._c.tpun_read_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]

    def version(self) -> str:
        return self._c.tpun_version().decode()

    def enum_accel(self, dev_dir: str) -> List[str]:
        buf = ctypes.create_string_buffer(64 * 1024)
        n = self._c.tpun_enum_accel(dev_dir.encode(), buf, len(buf))
        if n <= 0:
            return []
        return buf.value.decode().split("\n")

    def fd_holders(self, dev_path: str, proc_dir: str) -> List[int]:
        arr = (ctypes.c_int * 1024)()
        n = self._c.tpun_fd_holders(dev_path.encode(), proc_dir.encode(), arr, 1024)
        if n <= 0:
            return []
        return list(arr[: min(n, 1024)])

    def read_file(self, path: str) -> Optional[str]:
        buf = ctypes.create_string_buffer(64 * 1024)
        n = self._c.tpun_read_file(path.encode(), buf, len(buf))
        if n < 0:
            return None
        return buf.value.decode(errors="replace")


def _candidate_paths() -> List[str]:
    paths = []
    env = os.environ.get("TPUNODE_LIB")
    if env:
        paths.append(env)
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    paths.append(os.path.join(here, "native", "build", "libtpunode.so"))
    paths.append("libtpunode.so")
    return paths


def native_lib() -> Optional[_NativeLib]:
    """Load (once) and return the native library, or None."""
    global _loaded, _lib
    with _lock:
        if _loaded:
            return _lib
        _loaded = True
        for path in _candidate_paths():
            try:
                _lib = _NativeLib(ctypes.CDLL(path))
                return _lib
            except OSError:
                continue
        return None
