"""ctypes binding to the native node-agent core (native/tpunode.cc).

Loads ``libtpunode.so`` from (in order) $TPUNODE_LIB, the repo's
``native/build`` directory, or the system loader. Returns None when absent so
callers fall back to the pure-Python implementations with identical
semantics — the library is an optimization for the syscall-heavy polling
paths (full /proc fd sweeps each drain check), not a requirement.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

_lock = threading.Lock()
_loaded = False
_lib: Optional["_NativeLib"] = None


class _NativeLib:
    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._c = cdll
        self._c.tpun_version.restype = ctypes.c_char_p
        self._c.tpun_enum_accel.restype = ctypes.c_int
        self._c.tpun_enum_accel.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        self._c.tpun_fd_holders.restype = ctypes.c_int
        self._c.tpun_fd_holders.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        self._c.tpun_read_file.restype = ctypes.c_int
        self._c.tpun_read_file.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
        self._c.tpun_fd_holders_multi.restype = ctypes.c_int
        self._c.tpun_fd_holders_multi.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ]
        self._c.tpun_proc_name.restype = ctypes.c_int
        self._c.tpun_proc_name.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        self._c.tpun_watch_dev.restype = ctypes.c_int
        self._c.tpun_watch_dev.argtypes = [ctypes.c_char_p, ctypes.c_int]

    def version(self) -> str:
        return self._c.tpun_version().decode()

    def enum_accel(self, dev_dir: str) -> List[str]:
        buf = ctypes.create_string_buffer(64 * 1024)
        n = self._c.tpun_enum_accel(dev_dir.encode(), buf, len(buf))
        if n <= 0:
            return []
        return buf.value.decode().split("\n")

    def fd_holders(self, dev_path: str, proc_dir: str) -> List[int]:
        arr = (ctypes.c_int * 1024)()
        n = self._c.tpun_fd_holders(dev_path.encode(), proc_dir.encode(), arr, 1024)
        if n <= 0:
            return []
        return list(arr[: min(n, 1024)])

    def read_file(self, path: str) -> Optional[str]:
        buf = ctypes.create_string_buffer(64 * 1024)
        n = self._c.tpun_read_file(path.encode(), buf, len(buf))
        if n < 0:
            return None
        return buf.value.decode(errors="replace")

    def fd_holders_multi(self, dev_paths: List[str], proc_dir: str) -> "dict[str, List[int]]":
        """Holder pids per device path, attributed in a single /proc sweep:
        the C side emits (pid, path_index) pairs directly. Raises OSError on
        a failed sweep — callers guard drains, so an error must surface as
        UNKNOWN, never read as idle (matching the fallback, which propagates
        anything but a missing proc dir)."""
        if not dev_paths:
            return {}
        max_pairs = 4096
        pairs = (ctypes.c_int * (2 * max_pairs))()
        total = self._c.tpun_fd_holders_multi(
            "\n".join(dev_paths).encode(), proc_dir.encode(), pairs, max_pairs
        )
        out: dict[str, List[int]] = {p: [] for p in dev_paths}
        if total < 0:
            if not os.path.isdir(proc_dir):
                return out  # absent proc tree = no holders (fallback parity)
            raise OSError(f"native fd sweep of {proc_dir} failed")
        for i in range(min(total, max_pairs)):
            pid, idx = pairs[2 * i], pairs[2 * i + 1]
            if 0 <= idx < len(dev_paths):
                out[dev_paths[idx]].append(pid)
        return out

    def proc_name(self, proc_dir: str, pid: int) -> str:
        buf = ctypes.create_string_buffer(256)
        n = self._c.tpun_proc_name(proc_dir.encode(), pid, buf, len(buf))
        if n <= 0:
            return ""
        return buf.value.decode(errors="replace")

    def watch_dev(self, dev_dir: str, timeout_ms: int) -> int:
        """1 = a device node changed under dev_dir, 0 = timeout, -1 = error
        (caller falls back to polling)."""
        return self._c.tpun_watch_dev(dev_dir.encode(), timeout_ms)


def _candidate_paths() -> List[str]:
    paths = []
    env = os.environ.get("TPUNODE_LIB")
    if env:
        paths.append(env)
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    paths.append(os.path.join(here, "native", "build", "libtpunode.so"))
    paths.append("libtpunode.so")
    return paths


def native_lib() -> Optional[_NativeLib]:
    """Load (once) and return the native library, or None."""
    global _loaded, _lib
    with _lock:
        if _loaded:
            return _lib
        _loaded = True
        for path in _candidate_paths():
            try:
                _lib = _NativeLib(ctypes.CDLL(path))
                return _lib
            except OSError:
                continue
        return None
