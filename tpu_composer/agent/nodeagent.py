"""NodeAgent interface + LocalNodeAgent (real-host implementation).

The per-operation mapping from the reference's GPU plumbing (internal/utils):

| reference (gpus.go)                         | TPU node agent                       |
|---------------------------------------------|--------------------------------------|
| EnsureGPUDriverExists (:86, modinfo/chroot) | ensure_driver: libtpu present?       |
| CheckGPUVisible (:207, nvidia-smi/RS scan)  | check_visible: accel nodes enumerate |
| CheckNoGPULoads (:241, query-compute-apps)  | check_no_loads: /proc open-fd scan   |
| DrainGPU (:352, persistence off→fd check→   | drain: taint → fd check → unbind     |
|   rm node→nvidia-smi drain/sysfs remove)    |   accel node → verify gone           |
| CreateDeviceTaint/Delete/Has (:894-977)     | taint/untaint/has_taint              |
| RestartDaemonset / TerminateKubeletPlugin   | refresh_device_stack: (re)write CDI  |
|   (nodes.go:35, gpus.go:1127)               |   specs + signal runtime            |

The reference reaches nodes via SPDY pod-exec into privileged pods
(gpus.go:1040-1067); our LocalNodeAgent runs *on* the node (deployed as the
node-agent daemonset) and the controller talks to it through this interface —
in-process for single-box runs, RPC in a cluster. The interface is the
dependency-injection seam the tests use (SURVEY.md §4 takeaway: prefer DI
over gomonkey).
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from tpu_composer.agent import cdi as cdimod
from tpu_composer.agent.native import native_lib


class AgentError(Exception):
    pass


class DeviceBusyError(AgentError):
    """A process still holds the device open — drain must not proceed
    (the reference's open-fd guard, gpus.go:416-439)."""


class DriverType:
    NONE = "none"
    HOST = "host"  # libtpu on the host image
    CONTAINER = "container"  # libtpu supplied by a driver container


# Ceiling on a single wait_device_event block, shared by both sides of the
# RPC seam (serve.py enforces it, remote.py clamps to it so a client never
# believes a longer watch was held than the server actually armed).
MAX_WATCH_S = 30.0


class NodeAgent:
    """All methods take the node name; implementations may ignore it (a local
    agent serves exactly one node) or route RPC (a cluster agent client)."""

    def ensure_driver(self, node: str) -> str:
        """Verify the TPU runtime stack exists; returns a DriverType.
        Raises AgentError when no usable driver is found
        (EnsureGPUDriverExists, gpus.go:86-95)."""
        raise NotImplementedError

    def check_visible(self, node: str, device_ids: List[str], group: str = "") -> bool:
        """All chips of the group enumerate on the host
        (CheckGPUVisible, gpus.go:207-239). ``group`` is the CDI publication
        name, letting implementations distinguish this group's device nodes
        from co-located groups'."""
        raise NotImplementedError

    def check_no_loads(self, node: str, device_ids: List[str], group: str = "") -> bool:
        """No process holds the chips open
        (CheckNoGPULoads, gpus.go:241-350)."""
        raise NotImplementedError

    def drain(self, node: str, device_ids: List[str], force: bool = False,
              group: str = "") -> None:
        """Quiesce and remove the chips from the host device stack. Raises
        DeviceBusyError if loads remain and not force
        (DrainGPU, gpus.go:352-865)."""
        raise NotImplementedError

    def refresh_device_stack(
        self,
        node: str,
        spec: Optional[cdimod.CdiSpec] = None,
        remove_name: str = "",
    ) -> None:
        """Publish (or retract) the chip group to container workloads — CDI
        spec write/remove (replaces daemonset restarts,
        composableresource_controller.go:252-286)."""
        raise NotImplementedError

    def wait_device_event(self, node: str = "", timeout: float = 1.0) -> bool:
        """Block until a device node appears/vanishes on the node, or
        timeout; True iff an event fired. Default: no watch capability —
        callers degrade to polling."""
        return False

    # -- scheduling quarantine (DeviceTaintRule analog, gpus.go:894-977) ---
    def create_device_taint(self, node: str, device_ids: List[str], reason: str) -> None:
        raise NotImplementedError

    def delete_device_taint(self, node: str, device_ids: List[str]) -> None:
        raise NotImplementedError

    def has_device_taint(self, node: str, device_id: str) -> bool:
        raise NotImplementedError


class LocalNodeAgent(NodeAgent):
    """Operates on the local host's real device stack.

    Uses the native library (native/tpunode.cc) for device enumeration and
    /proc fd scanning when built, with pure-Python fallbacks. Paths are
    parameterized for tests and non-standard images.
    """

    def __init__(
        self,
        dev_dir: str = "/dev",
        proc_dir: str = "/proc",
        cdi_dir: str = cdimod.DEFAULT_CDI_DIR,
        libtpu_paths: Optional[List[str]] = None,
        state_dir: str = "/var/run/tpu-composer",
    ) -> None:
        self.dev_dir = dev_dir
        self.proc_dir = proc_dir
        self.cdi_dir = cdi_dir
        self.libtpu_paths = libtpu_paths or [
            "/lib/libtpu.so",
            "/usr/lib/libtpu.so",
            "/usr/local/lib/libtpu.so",
            "/home/kubernetes/bin/libtpu.so",
        ]
        self.state_dir = state_dir
        self._native = native_lib()

    # ------------------------------------------------------------------
    def ensure_driver(self, node: str) -> str:
        for p in self.libtpu_paths:
            if os.path.exists(p):
                return DriverType.HOST
        # A driver container mounts libtpu under /run (the analog of the
        # reference's containerized driver root /run/nvidia/driver, gpus.go:47)
        if os.path.exists("/run/libtpu/libtpu.so"):
            return DriverType.CONTAINER
        raise AgentError(f"no libtpu found on {node}; looked in {self.libtpu_paths}")

    def _accel_nodes(self) -> List[str]:
        if self._native is not None:
            return self._native.enum_accel(self.dev_dir)
        try:
            return sorted(
                os.path.join(self.dev_dir, fn)
                for fn in os.listdir(self.dev_dir)
                if fn.startswith("accel")
            )
        except FileNotFoundError:
            return []

    # -- device-node claims: which accel paths belong to which group -------
    # Recorded at CDI publish time so visibility/load checks are per-group
    # rather than count-based (co-located groups must not satisfy each
    # other's checks).
    def _claims_dir(self) -> str:
        return os.path.join(self.state_dir, "claims")

    def _claim_path(self, group: str) -> str:
        return os.path.join(self._claims_dir(), group.replace("/", "_") + ".json")

    def _record_claim(self, group: str, device_nodes: List[str]) -> None:
        # CDI specs carry container-visible paths (/dev/accelN or
        # /dev/vfio/N); rebase onto this agent's dev_dir so checks work under
        # a relocated host root (tests, chrooted agents). Per-chip nodes are
        # accelN and numbered vfio group nodes; the shared vfio control node
        # (/dev/vfio/vfio) is not per-group and is skipped.
        paths = []
        for p in device_nodes:
            base = os.path.basename(p)
            parent = os.path.basename(os.path.dirname(p))
            if base.startswith("accel"):
                paths.append(os.path.join(self.dev_dir, base))
            elif parent == "vfio" and base != "vfio":
                paths.append(os.path.join(self.dev_dir, "vfio", base))
        os.makedirs(self._claims_dir(), exist_ok=True)
        with open(self._claim_path(group), "w") as f:
            json.dump(sorted(paths), f)

    def _drop_claim(self, group: str) -> None:
        try:
            os.remove(self._claim_path(group))
        except FileNotFoundError:
            pass

    def list_composed_devices(self) -> Dict[str, List[str]]:
        """Public claim inventory: composed group name -> its device nodes.

        This is the contract the kubelet device plugin builds its device
        list from (agent/plugin.py lister_from_agent) — a stable accessor,
        not internal state (ADVICE r2: the plugin previously reached into
        _claims())."""
        return self._claims()

    def _claims(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        try:
            entries = os.listdir(self._claims_dir())
        except FileNotFoundError:
            return out
        for fn in entries:
            if fn.endswith(".json"):
                with open(os.path.join(self._claims_dir(), fn)) as f:
                    out[fn[:-5]] = json.load(f)
        return out

    def _group_paths(self, group: str, count: int) -> List[str]:
        """The accel paths to inspect for a group: its claimed nodes when the
        claim exists, else the host's accel nodes NOT claimed by others."""
        claims = self._claims()
        key = group.replace("/", "_") if group else ""
        if key and key in claims:
            return claims[key]
        others = {p for g, paths in claims.items() if g != key for p in paths}
        return [p for p in self._accel_nodes() if p not in others][: count or None]

    def check_visible(self, node: str, device_ids: List[str], group: str = "") -> bool:
        # Claimed paths may be accel or vfio nodes; presence on the host is
        # what "visible" means either way (CheckGPUVisible, gpus.go:207-239).
        paths = self._group_paths(group, len(device_ids))
        present = [p for p in paths if os.path.exists(p)]
        return len(present) >= len(device_ids) and bool(device_ids)

    def _holders(self, dev_path: str) -> List[int]:
        return self._holders_multi([dev_path]).get(dev_path, [])

    def _holders_multi(self, dev_paths: List[str]) -> Dict[str, List[int]]:
        """Holder pids for every path in ONE /proc sweep (a group drain
        checks 4+ device nodes; per-path sweeps scale O(paths x processes))."""
        if not dev_paths:
            return {}
        if self._native is not None:
            return self._native.fd_holders_multi(dev_paths, self.proc_dir)
        wanted = set(dev_paths)
        out: Dict[str, List[int]] = {p: [] for p in dev_paths}
        try:
            entries = os.listdir(self.proc_dir)
        except FileNotFoundError:
            return out
        for entry in entries:
            if not entry.isdigit():
                continue
            fd_dir = os.path.join(self.proc_dir, entry, "fd")
            try:
                for fd in os.listdir(fd_dir):
                    try:
                        target = os.readlink(os.path.join(fd_dir, fd))
                    except OSError:
                        continue
                    if target in wanted and int(entry) not in out[target]:
                        out[target].append(int(entry))
            except OSError:
                continue
        return out

    def _proc_name(self, pid: int) -> str:
        if self._native is not None:
            return self._native.proc_name(self.proc_dir, pid)
        try:
            with open(os.path.join(self.proc_dir, str(pid), "comm")) as f:
                return f.read().strip()
        except OSError:
            return ""

    def _describe_holders(self, busy: Dict[str, List[int]]) -> str:
        """'/dev/accel0 held by 1234(python3)' — named-workload diagnostics,
        the parity point for the reference's query-compute-apps pid+name
        reporting (gpus.go:241-350)."""
        parts = []
        for path in sorted(busy):
            procs = ", ".join(
                f"{pid}({self._proc_name(pid) or '?'})" for pid in busy[path]
            )
            parts.append(f"{path} held by {procs}")
        return "; ".join(parts)

    def check_no_loads(self, node: str, device_ids: List[str], group: str = "") -> bool:
        holders = self._holders_multi(self._group_paths(group, len(device_ids)))
        return not any(holders.values())

    def drain(self, node: str, device_ids: List[str], force: bool = False,
              group: str = "") -> None:
        paths = self._group_paths(group, len(device_ids))
        if not force:
            busy = {p: h for p, h in self._holders_multi(paths).items() if h}
            if busy:
                raise DeviceBusyError(self._describe_holders(busy))
        # On a real fabric the unbind happens through the fabric manager; the
        # host-side publication retraction is targeted per group via
        # refresh_device_stack(remove_name=...) — drain must NOT touch CDI
        # specs, or it would destroy co-located groups' publications.

    def refresh_device_stack(self, node, spec=None, remove_name=""):
        if spec is not None:
            cdimod.write_cdi_spec(self.cdi_dir, spec)
            self._record_claim(spec.name, spec.device_nodes)
        if remove_name:
            cdimod.remove_cdi_spec(self.cdi_dir, remove_name)
            self._drop_claim(remove_name)

    def _dev_snapshot(self) -> set:
        try:
            return set(os.listdir(self.dev_dir))
        except OSError:
            return set()

    def wait_device_event(self, node: str = "", timeout: float = 1.0) -> bool:
        """Block until a device node appears/vanishes under dev_dir, or
        timeout. True iff an event fired. ``node`` is ignored (a local agent
        serves exactly one host). Native path is inotify (tpun_watch_dev);
        the fallback compares directory snapshots on a 50ms cadence. This
        powers the DeviceEventWatcher runnable that replaces fixed
        visibility polling with event-driven reconciles (BASELINE.md's
        biggest latency lever).

        The native inotify watch is armed per call, so an event landing in
        the gap between two calls would be invisible to inotify; a
        cross-call directory snapshot diff catches exactly those (advisor
        round-1 finding): any change since the previous call reports as an
        immediate event."""
        timeout = max(0.0, timeout)
        current = self._dev_snapshot()
        last = getattr(self, "_last_dev_snapshot", None)
        self._last_dev_snapshot = current
        if last is not None and current != last:
            return True
        if self._native is not None:
            rc = self._native.watch_dev(self.dev_dir, int(timeout * 1000))
            if rc >= 0:
                if rc == 1:
                    self._last_dev_snapshot = self._dev_snapshot()
                    return True
                return False
            # fall through to the polling fallback on error
        import time as _time

        deadline = _time.monotonic() + timeout
        while _time.monotonic() < deadline:
            _time.sleep(0.05)
            now = self._dev_snapshot()
            if now != current:
                self._last_dev_snapshot = now
                return True
        return False

    # -- taints are marker files under state_dir ------------------------
    def _taint_path(self, device_id: str) -> str:
        safe = device_id.replace("/", "_")
        return os.path.join(self.state_dir, "taints", safe)

    def create_device_taint(self, node, device_ids, reason):
        os.makedirs(os.path.join(self.state_dir, "taints"), exist_ok=True)
        for d in device_ids:
            with open(self._taint_path(d), "w") as f:
                f.write(reason)

    def delete_device_taint(self, node, device_ids):
        for d in device_ids:
            try:
                os.remove(self._taint_path(d))
            except FileNotFoundError:
                pass

    def has_device_taint(self, node, device_id) -> bool:
        return os.path.exists(self._taint_path(device_id))
