"""Kubelet device plugin — composed chips become schedulable pod resources.

Round 1 wrote CDI specs and kept taints node-local; nothing a scheduler could
see, so workloads could not actually *request* a composed chip (VERDICT r1
missing #2). This plugin closes that gap on the DEVICE_PLUGIN path, speaking
the real kubelet gRPC wire protocol (deviceplugin.proto, v1beta1):

- serves ``DevicePlugin`` (ListAndWatch stream + Allocate) on a unix socket
  under the kubelet plugin directory;
- registers with the kubelet's ``Registration`` service, advertising the
  extended resource ``tpu.composer.dev/chips``;
- sources its device list from the node agent's CDI claim state, so the
  plugin's advertisement is always exactly what the operator attached;
- ``Allocate`` answers with CDI device names plus raw ``/dev/accel*``
  device specs, and injects ``TPU_VISIBLE_CHIPS`` for the runtime.

Reference analog: the reference depends on NVIDIA's external device-plugin
daemonset and merely restarts it after attach/detach
(composableresource_controller.go:252-270, utils/nodes.go:35-76). Building
the plugin into the node agent removes the restart dance entirely: the agent
nudges ``notify()`` on attach/detach and the ListAndWatch stream pushes the
new device list immediately.

gRPC wiring is hand-rolled against the generated protobuf messages (the
image has grpcio + protoc but no grpc_tools stub generator).
"""

from __future__ import annotations

import concurrent.futures
import logging
import os
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import grpc

from tpu_composer.agent import deviceplugin_pb2 as pb

RESOURCE_NAME = "tpu.composer.dev/chips"
KUBELET_SOCKET = "kubelet.sock"
PLUGIN_SOCKET = "tpu-composer.sock"
API_VERSION = "v1beta1"

# list_devices() -> [(device_id, healthy, dev_path, cdi_name)]
DeviceLister = Callable[[], Sequence[Tuple[str, bool, str, str]]]


class TPUDevicePlugin:
    """One plugin instance per node agent."""

    def __init__(
        self,
        list_devices: DeviceLister,
        plugin_dir: str,
        node_name: str = "",
        resource_name: str = RESOURCE_NAME,
    ) -> None:
        self.list_devices = list_devices
        self.plugin_dir = plugin_dir
        self.node_name = node_name
        self.resource_name = resource_name
        self.log = logging.getLogger("TPUDevicePlugin")
        self._server: Optional[grpc.Server] = None
        self._changed = threading.Condition()
        self._stopped = threading.Event()
        self.allocations: Dict[str, List[str]] = {}  # container hint -> ids

    # ------------------------------------------------------------------
    # service handlers
    # ------------------------------------------------------------------
    def _options(self, request, context) -> pb.DevicePluginOptions:
        return pb.DevicePluginOptions(pre_start_required=False)

    def _snapshot(self) -> List[pb.Device]:
        return [
            pb.Device(ID=dev_id, health="Healthy" if healthy else "Unhealthy")
            for dev_id, healthy, _, _ in self.list_devices()
        ]

    def _list_and_watch(self, request, context):
        """Stream the device list; push an update whenever notify() fires.

        The kubelet holds this stream open for the plugin's lifetime and
        folds every response into node allocatable."""
        last: Optional[List[Tuple[str, str]]] = None
        while not self._stopped.is_set() and context.is_active():
            devices = self._snapshot()
            key = sorted((d.ID, d.health) for d in devices)
            if key != last:
                last = key
                yield pb.ListAndWatchResponse(devices=devices)
            with self._changed:
                self._changed.wait(timeout=1.0)

    def _allocate(self, request: pb.AllocateRequest, context) -> pb.AllocateResponse:
        byid = {d[0]: d for d in self.list_devices()}
        responses = []
        for creq in request.container_requests:
            mounts: List[pb.Mount] = []
            devspecs: List[pb.DeviceSpec] = []
            cdi: List[pb.CDIDevice] = []
            visible: List[str] = []
            for dev_id in creq.devices_ids:
                dev = byid.get(dev_id)
                if dev is None:
                    context.abort(
                        grpc.StatusCode.NOT_FOUND,
                        f"device {dev_id} not available on this node",
                    )
                _, _, dev_path, cdi_name = dev
                if cdi_name:
                    cdi.append(pb.CDIDevice(name=cdi_name))
                if dev_path:
                    devspecs.append(
                        pb.DeviceSpec(
                            container_path=dev_path,
                            host_path=dev_path,
                            permissions="rw",
                        )
                    )
                visible.append(dev_id)
            self.allocations[",".join(sorted(visible))] = visible
            responses.append(
                pb.ContainerAllocateResponse(
                    envs={"TPU_VISIBLE_CHIPS": ",".join(visible)},
                    devices=devspecs,
                    cdi_devices=cdi,
                )
            )
        return pb.AllocateResponse(container_responses=responses)

    def _pre_start(self, request, context) -> pb.PreStartContainerResponse:
        return pb.PreStartContainerResponse()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def socket_path(self) -> str:
        return os.path.join(self.plugin_dir, PLUGIN_SOCKET)

    def notify(self) -> None:
        """Device set changed (attach/detach) — push to the kubelet now."""
        with self._changed:
            self._changed.notify_all()

    def start(self) -> None:
        os.makedirs(self.plugin_dir, exist_ok=True)
        try:
            os.remove(self.socket_path)
        except FileNotFoundError:
            pass
        server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=4))
        handlers = {
            "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
                self._options,
                request_deserializer=pb.Empty.FromString,
                response_serializer=pb.DevicePluginOptions.SerializeToString,
            ),
            "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                self._list_and_watch,
                request_deserializer=pb.Empty.FromString,
                response_serializer=pb.ListAndWatchResponse.SerializeToString,
            ),
            "Allocate": grpc.unary_unary_rpc_method_handler(
                self._allocate,
                request_deserializer=pb.AllocateRequest.FromString,
                response_serializer=pb.AllocateResponse.SerializeToString,
            ),
            "PreStartContainer": grpc.unary_unary_rpc_method_handler(
                self._pre_start,
                request_deserializer=pb.PreStartContainerRequest.FromString,
                response_serializer=pb.PreStartContainerResponse.SerializeToString,
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(f"{API_VERSION}.DevicePlugin", handlers),)
        )
        server.add_insecure_port(f"unix:{self.socket_path}")
        server.start()
        self._server = server
        self.log.info("device plugin serving on %s", self.socket_path)

    def register_with_kubelet(self, kubelet_socket: Optional[str] = None) -> None:
        """Announce ourselves: kubelet then dials our endpoint back."""
        sock = kubelet_socket or os.path.join(self.plugin_dir, KUBELET_SOCKET)
        with grpc.insecure_channel(f"unix:{sock}") as channel:
            register = channel.unary_unary(
                f"/{API_VERSION}.Registration/Register",
                request_serializer=pb.RegisterRequest.SerializeToString,
                response_deserializer=pb.Empty.FromString,
            )
            register(
                pb.RegisterRequest(
                    version=API_VERSION,
                    endpoint=PLUGIN_SOCKET,
                    resource_name=self.resource_name,
                    options=pb.DevicePluginOptions(pre_start_required=False),
                ),
                timeout=5.0,
            )
        self.log.info("registered %s with kubelet at %s", self.resource_name, sock)

    def stop(self) -> None:
        self._stopped.set()
        self.notify()
        if self._server is not None:
            self._server.stop(grace=1.0).wait(timeout=5.0)
            self._server = None
        try:
            os.remove(self.socket_path)
        except FileNotFoundError:
            pass


def lister_from_agent(agent) -> DeviceLister:
    """Adapt a LocalNodeAgent's CDI claim state to the plugin's device list.

    Each claimed group contributes its chips; device id = ``<group>/<idx>``
    with the CDI qualified name for runtime injection. Unclaimed chips are
    not advertised — the scheduler only sees what the operator composed.
    Consumes the agent's public list_composed_devices() contract."""

    def list_devices():
        out = []
        for group, dev_nodes in sorted(agent.list_composed_devices().items()):
            for idx, dev in enumerate(sorted(dev_nodes)):
                out.append(
                    (f"{group}/{idx}", True, dev, f"tpu.composer.dev/chip={group}")
                )
        return out

    return list_devices
