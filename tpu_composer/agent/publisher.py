"""DevicePublisher — keep one ResourceSlice per node in cluster state.

The scheduler-visible half of the DRA path (VERDICT r1 missing #2): after the
fabric attaches a chip group and the CDI spec is written, the resource
controller publishes the group's chips into the node's ResourceSlice; on
detach it retracts them. Quarantine is a DeviceTaintRule per device uuid
created before the drain and removed after invisibility — the exact ordering
the reference uses (composableresource_controller.go:333-420: taint →
drain → remove → untaint; rule objects at utils/gpus.go:894-975).

Works against both the in-proc Store and KubeStore (conflict-retried CAS on
the per-node slice object).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from tpu_composer.api.dra import (
    DeviceTaintRule,
    DeviceTaintRuleSpec,
    ResourceSlice,
    ResourceSliceSpec,
    SliceDevice,
    taint_rule_name,
)
from tpu_composer.api.meta import ObjectMeta
from tpu_composer.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)


def slice_object_name(node: str) -> str:
    return f"{node}-tpu.composer.dev"


def node_quarantine_name(node: str) -> str:
    """Deterministic DeviceTaintRule name for a whole-node quarantine
    (device_uuid empty, node_name set — the 'whole node' arm the
    DeviceTaintRuleSpec docstring reserves)."""
    return "quarantine-node-" + node.replace("/", "-").lower()


def is_node_quarantine_marker(rule) -> bool:
    """THE whole-node quarantine marker shape test (node_name set,
    device_uuid empty): the allocator gate, the syncer's stale-marker
    sweep and quarantined_nodes() all consume this one predicate so the
    encoding can't drift between them."""
    return bool(rule.spec.node_name) and not rule.spec.device_uuid


def retire_node(fabric, publisher, node: str) -> None:
    """Host-left-the-fleet retirement: forget its circuit breaker (no-op
    for providers without per-node breakers) and delete its durable
    quarantine marker, so a recreated same-name node starts allocatable.
    Shared by the resource controller's node-DELETED mapper, its
    _gc_node_gone retry and the syncer's stale-marker sweep — one ritual,
    no drift (same reason is_node_quarantine_marker exists)."""
    forget = getattr(fabric, "forget_node", None)
    if callable(forget):
        forget(node)
    publisher.clear_node_quarantine(node)


def node_quarantined(store, node: str) -> bool:
    """Point check for ONE node's quarantine marker. Allocation-path code
    deliberately does NOT use this — it calls quarantined_nodes() once per
    pass to avoid per-candidate wire GETs; this is for single-node probes
    (publisher API, operators, tests)."""
    return store.try_get(DeviceTaintRule, node_quarantine_name(node)) is not None


def quarantined_nodes(store) -> set:
    """Every host under a whole-node quarantine marker, in one list call
    (shape test: is_node_quarantine_marker) — the request allocator and
    the resource controller's quarantine gate both consume this so the
    encoding can't drift."""
    return {
        r.spec.node_name
        for r in store.list(DeviceTaintRule)
        if is_node_quarantine_marker(r)
    }


class DevicePublisher:
    def __init__(self, store, retries: int = 5) -> None:
        self.store = store
        self.retries = retries
        self.log = logging.getLogger("DevicePublisher")

    # ------------------------------------------------------------------
    # publication
    # ------------------------------------------------------------------
    def publish_group(
        self,
        node: str,
        group: str,
        device_ids: List[str],
        model: str,
        cdi_device_id: str = "",
        dev_paths: Optional[List[str]] = None,
    ) -> None:
        """Add (or refresh) one composed group's chips on the node's slice."""
        devices = [
            SliceDevice(
                name=f"{group}-{i}",
                uuid=uid,
                model=model,
                slice_name=group,
                cdi_device_id=cdi_device_id,
                dev_path=(dev_paths[i] if dev_paths and i < len(dev_paths) else ""),
            )
            for i, uid in enumerate(device_ids)
        ]
        self._mutate_slice(node, group, devices)

    def retract_group(self, node: str, group: str) -> None:
        """Remove a group's chips from the node's slice."""
        self._mutate_slice(node, group, [])

    def _mutate_slice(
        self, node: str, group: str, new_devices: List[SliceDevice]
    ) -> None:
        name = slice_object_name(node)
        for _ in range(self.retries):
            existing = self.store.try_get(ResourceSlice, name)
            if existing is None:
                if not new_devices:
                    return
                try:
                    self.store.create(
                        ResourceSlice(
                            metadata=ObjectMeta(name=name),
                            spec=ResourceSliceSpec(
                                node_name=node, pool=node, devices=new_devices
                            ),
                        )
                    )
                    return
                except AlreadyExistsError:
                    continue  # raced another publisher — retry as update
            kept = [d for d in existing.spec.devices if d.slice_name != group]
            existing.spec.devices = kept + new_devices
            try:
                if existing.spec.devices:
                    self.store.update(existing)
                else:
                    # empty slice → delete the object (kubelet plugins do the
                    # same; an empty slice advertises nothing)
                    self.store.delete(ResourceSlice, name)
                return
            except (ConflictError, NotFoundError):
                continue
        self.log.warning("slice update for %s kept conflicting; giving up", name)

    # ------------------------------------------------------------------
    # visibility (the reference's CheckGPUVisible DRA arm, gpus.go:207-239)
    # ------------------------------------------------------------------
    def devices_visible(self, node: str, device_ids: List[str]) -> bool:
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return False
        present = {d.uuid for d in sl.spec.devices}
        return all(uid in present for uid in device_ids)

    def devices_invisible(self, node: str, device_ids: List[str]) -> bool:
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return True
        present = {d.uuid for d in sl.spec.devices}
        return not any(uid in present for uid in device_ids)

    # ------------------------------------------------------------------
    # quarantine (gpus.go:894-975)
    # ------------------------------------------------------------------
    def create_taints(self, node: str, device_ids: List[str], reason: str) -> None:
        for uid in device_ids:
            name = taint_rule_name(uid)
            if self.store.try_get(DeviceTaintRule, name) is not None:
                continue
            try:
                self.store.create(
                    DeviceTaintRule(
                        metadata=ObjectMeta(name=name),
                        spec=DeviceTaintRuleSpec(
                            device_uuid=uid, node_name=node, reason=reason
                        ),
                    )
                )
            except AlreadyExistsError:
                pass

    def delete_taints(self, device_ids: List[str]) -> None:
        for uid in device_ids:
            try:
                self.store.delete(DeviceTaintRule, taint_rule_name(uid))
            except NotFoundError:
                pass

    def tainted(self, device_uuid: str) -> bool:
        return self.store.try_get(DeviceTaintRule, taint_rule_name(device_uuid)) is not None

    # ------------------------------------------------------------------
    # node quarantine (fabric resilience layer, docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def quarantine_node(self, node: str, reason: str) -> None:
        """Durable node-level quarantine marker. Unlike the per-device
        detach taints, this survives the failing ComposableResource's
        deletion — it is what keeps the allocator from re-placing
        replacement capacity onto the host whose attach path just burned an
        entire budget. Cleared by an operator (or test) once the fabric
        path is repaired."""
        name = node_quarantine_name(node)
        if self.store.try_get(DeviceTaintRule, name) is not None:
            return
        try:
            self.store.create(
                DeviceTaintRule(
                    metadata=ObjectMeta(name=name),
                    spec=DeviceTaintRuleSpec(node_name=node, reason=reason),
                )
            )
        except AlreadyExistsError:
            pass

    def clear_node_quarantine(self, node: str) -> None:
        try:
            self.store.delete(DeviceTaintRule, node_quarantine_name(node))
        except NotFoundError:
            pass

    def node_quarantined(self, node: str) -> bool:
        return node_quarantined(self.store, node)

    def claimable(self, node: str) -> List[SliceDevice]:
        """What a scheduler could still place on: published and untainted.
        (Used by tests' scheduler simulation and the syncer's accounting.)"""
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return []
        return [d for d in sl.spec.devices if not self.tainted(d.uuid)]


class InventoryPublisher:
    """Event-fed ResourceSlice drift repair (wire plane v2, part c).

    The publication writes themselves ride the attach/detach paths; what
    used to require a poll is noticing that a node's published slice no
    longer matches what the fabric actually has attached (slice object
    deleted by an operator, publication lost to a crash between attach and
    publish). This runnable re-checks on fabric *inventory events* — the
    push signal that composed capacity changed — with the timed pass
    demoted to a ``period × fallback_multiplier`` safety net while the
    event session streams (the same shape as UpstreamSyncer's relist
    demotion). At constant cluster state it performs zero wire ops beyond
    the cache-fed reads: visibility checks go through the informer cache,
    and ``get_resources()`` runs only when an event or the safety net
    fires.

    Repair policy is deliberately conservative: only a group whose chips
    are *entirely* unpublished on its node is re-published (a partial set
    is an in-flight controller mutation, not drift), and only when its
    owning ComposableResource is findable, Online, not terminating, and
    has no pending fabric op. A repaired entry carries no CDI device id —
    the controller's own publication (which knows it) wins on the next
    reconcile since _mutate_slice replaces the group's entries wholesale.
    """

    def __init__(
        self,
        store,
        fabric,
        session=None,
        period: float = 60.0,
        fallback_multiplier: float = 20.0,
    ) -> None:
        self.store = store
        self.fabric = fabric
        self.publisher = DevicePublisher(store)
        self.session = session
        self.period = period
        self.fallback_multiplier = max(1.0, fallback_multiplier)
        self.log = logging.getLogger("InventoryPublisher")
        self.repairs = 0  # introspection (tests / debug)
        self._wake = threading.Event()
        if session is not None:
            from tpu_composer.fabric.events import EVENT_INVENTORY

            def _on_event(evt, _kind=EVENT_INVENTORY):
                if evt.type == _kind:
                    self._wake.set()

            session.on_event(_on_event)
            session.on_gap(self._wake.set)

    def effective_period(self) -> float:
        if self.session is not None and self.session.healthy():
            return self.period * self.fallback_multiplier
        return self.period

    def reconcile_once(self) -> int:
        """One repair pass; returns how many groups were re-published."""
        from tpu_composer.api.types import (
            ComposableResource,
            RESOURCE_STATE_ONLINE,
        )
        from tpu_composer.fabric.provider import FabricError

        try:
            devices = self.fabric.get_resources()
            resources = {r.name: r for r in self.store.list(ComposableResource)}
        except FabricError:
            return 0  # fabric outage: nothing to diff against
        groups: dict = {}
        for dev in devices:
            if dev.node and dev.slice_name and dev.resource_name:
                groups.setdefault(
                    (dev.node, dev.slice_name, dev.resource_name), []
                ).append(dev)
        repaired = 0
        for (node, group, owner_name), devs in sorted(groups.items()):
            owner = resources.get(owner_name)
            if (
                owner is None
                or owner.being_deleted
                or owner.status.state != RESOURCE_STATE_ONLINE
                or owner.status.pending_op is not None
            ):
                continue  # mid-flight or dying: the controller owns this
            ids = [d.device_id for d in devs]
            if not self.publisher.devices_invisible(node, ids):
                continue  # fully or partially published: not our drift
            self.publisher.publish_group(node, group, ids, devs[0].model)
            self.log.warning(
                "republished %d chip(s) of %s on %s (slice publication had"
                " vanished while the fabric still reports the attachment)",
                len(ids), group, node,
            )
            repaired += 1
        self.repairs += repaired
        return repaired

    # Manager runnable entry point (same contract as UpstreamSyncer).
    def __call__(self, stop_event: threading.Event) -> None:
        from tpu_composer.fabric.events import doorbell_wait
        from tpu_composer.runtime.store import StoreError

        last_pass = float("-inf")
        while not stop_event.is_set():
            # Same burst coalescing as the syncer: churn rings the
            # inventory doorbell once per attach/detach, so repair
            # passes are floored at the base period instead of running
            # once per event.
            doorbell_wait(
                stop_event, self._wake,
                deadline=time.monotonic() + self.effective_period(),
                floor=last_pass + self.period,
            )
            if stop_event.is_set():
                return
            self._wake.clear()
            last_pass = time.monotonic()
            try:
                self.reconcile_once()
            except StoreError as e:
                self.log.warning("slice repair pass failed: %s", e)
