"""DevicePublisher — keep one ResourceSlice per node in cluster state.

The scheduler-visible half of the DRA path (VERDICT r1 missing #2): after the
fabric attaches a chip group and the CDI spec is written, the resource
controller publishes the group's chips into the node's ResourceSlice; on
detach it retracts them. Quarantine is a DeviceTaintRule per device uuid
created before the drain and removed after invisibility — the exact ordering
the reference uses (composableresource_controller.go:333-420: taint →
drain → remove → untaint; rule objects at utils/gpus.go:894-975).

Works against both the in-proc Store and KubeStore (conflict-retried CAS on
the per-node slice object).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from tpu_composer.api.dra import (
    DeviceTaintRule,
    DeviceTaintRuleSpec,
    ResourceSlice,
    ResourceSliceSpec,
    SliceDevice,
    taint_rule_name,
)
from tpu_composer.api.meta import ObjectMeta
from tpu_composer.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
)


def slice_object_name(node: str) -> str:
    return f"{node}-tpu.composer.dev"


def node_quarantine_name(node: str) -> str:
    """Deterministic DeviceTaintRule name for a whole-node quarantine
    (device_uuid empty, node_name set — the 'whole node' arm the
    DeviceTaintRuleSpec docstring reserves)."""
    return "quarantine-node-" + node.replace("/", "-").lower()


def is_node_quarantine_marker(rule) -> bool:
    """THE whole-node quarantine marker shape test (node_name set,
    device_uuid empty): the allocator gate, the syncer's stale-marker
    sweep and quarantined_nodes() all consume this one predicate so the
    encoding can't drift between them."""
    return bool(rule.spec.node_name) and not rule.spec.device_uuid


def retire_node(fabric, publisher, node: str) -> None:
    """Host-left-the-fleet retirement: forget its circuit breaker (no-op
    for providers without per-node breakers) and delete its durable
    quarantine marker, so a recreated same-name node starts allocatable.
    Shared by the resource controller's node-DELETED mapper, its
    _gc_node_gone retry and the syncer's stale-marker sweep — one ritual,
    no drift (same reason is_node_quarantine_marker exists)."""
    forget = getattr(fabric, "forget_node", None)
    if callable(forget):
        forget(node)
    publisher.clear_node_quarantine(node)


def node_quarantined(store, node: str) -> bool:
    """Point check for ONE node's quarantine marker. Allocation-path code
    deliberately does NOT use this — it calls quarantined_nodes() once per
    pass to avoid per-candidate wire GETs; this is for single-node probes
    (publisher API, operators, tests)."""
    return store.try_get(DeviceTaintRule, node_quarantine_name(node)) is not None


def quarantined_nodes(store) -> set:
    """Every host under a whole-node quarantine marker, in one list call
    (shape test: is_node_quarantine_marker) — the request allocator and
    the resource controller's quarantine gate both consume this so the
    encoding can't drift."""
    return {
        r.spec.node_name
        for r in store.list(DeviceTaintRule)
        if is_node_quarantine_marker(r)
    }


class DevicePublisher:
    def __init__(self, store, retries: int = 5) -> None:
        self.store = store
        self.retries = retries
        self.log = logging.getLogger("DevicePublisher")

    # ------------------------------------------------------------------
    # publication
    # ------------------------------------------------------------------
    def publish_group(
        self,
        node: str,
        group: str,
        device_ids: List[str],
        model: str,
        cdi_device_id: str = "",
        dev_paths: Optional[List[str]] = None,
    ) -> None:
        """Add (or refresh) one composed group's chips on the node's slice."""
        devices = [
            SliceDevice(
                name=f"{group}-{i}",
                uuid=uid,
                model=model,
                slice_name=group,
                cdi_device_id=cdi_device_id,
                dev_path=(dev_paths[i] if dev_paths and i < len(dev_paths) else ""),
            )
            for i, uid in enumerate(device_ids)
        ]
        self._mutate_slice(node, group, devices)

    def retract_group(self, node: str, group: str) -> None:
        """Remove a group's chips from the node's slice."""
        self._mutate_slice(node, group, [])

    def _mutate_slice(
        self, node: str, group: str, new_devices: List[SliceDevice]
    ) -> None:
        name = slice_object_name(node)
        for _ in range(self.retries):
            existing = self.store.try_get(ResourceSlice, name)
            if existing is None:
                if not new_devices:
                    return
                try:
                    self.store.create(
                        ResourceSlice(
                            metadata=ObjectMeta(name=name),
                            spec=ResourceSliceSpec(
                                node_name=node, pool=node, devices=new_devices
                            ),
                        )
                    )
                    return
                except AlreadyExistsError:
                    continue  # raced another publisher — retry as update
            kept = [d for d in existing.spec.devices if d.slice_name != group]
            existing.spec.devices = kept + new_devices
            try:
                if existing.spec.devices:
                    self.store.update(existing)
                else:
                    # empty slice → delete the object (kubelet plugins do the
                    # same; an empty slice advertises nothing)
                    self.store.delete(ResourceSlice, name)
                return
            except (ConflictError, NotFoundError):
                continue
        self.log.warning("slice update for %s kept conflicting; giving up", name)

    # ------------------------------------------------------------------
    # visibility (the reference's CheckGPUVisible DRA arm, gpus.go:207-239)
    # ------------------------------------------------------------------
    def devices_visible(self, node: str, device_ids: List[str]) -> bool:
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return False
        present = {d.uuid for d in sl.spec.devices}
        return all(uid in present for uid in device_ids)

    def devices_invisible(self, node: str, device_ids: List[str]) -> bool:
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return True
        present = {d.uuid for d in sl.spec.devices}
        return not any(uid in present for uid in device_ids)

    # ------------------------------------------------------------------
    # quarantine (gpus.go:894-975)
    # ------------------------------------------------------------------
    def create_taints(self, node: str, device_ids: List[str], reason: str) -> None:
        for uid in device_ids:
            name = taint_rule_name(uid)
            if self.store.try_get(DeviceTaintRule, name) is not None:
                continue
            try:
                self.store.create(
                    DeviceTaintRule(
                        metadata=ObjectMeta(name=name),
                        spec=DeviceTaintRuleSpec(
                            device_uuid=uid, node_name=node, reason=reason
                        ),
                    )
                )
            except AlreadyExistsError:
                pass

    def delete_taints(self, device_ids: List[str]) -> None:
        for uid in device_ids:
            try:
                self.store.delete(DeviceTaintRule, taint_rule_name(uid))
            except NotFoundError:
                pass

    def tainted(self, device_uuid: str) -> bool:
        return self.store.try_get(DeviceTaintRule, taint_rule_name(device_uuid)) is not None

    # ------------------------------------------------------------------
    # node quarantine (fabric resilience layer, docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def quarantine_node(self, node: str, reason: str) -> None:
        """Durable node-level quarantine marker. Unlike the per-device
        detach taints, this survives the failing ComposableResource's
        deletion — it is what keeps the allocator from re-placing
        replacement capacity onto the host whose attach path just burned an
        entire budget. Cleared by an operator (or test) once the fabric
        path is repaired."""
        name = node_quarantine_name(node)
        if self.store.try_get(DeviceTaintRule, name) is not None:
            return
        try:
            self.store.create(
                DeviceTaintRule(
                    metadata=ObjectMeta(name=name),
                    spec=DeviceTaintRuleSpec(node_name=node, reason=reason),
                )
            )
        except AlreadyExistsError:
            pass

    def clear_node_quarantine(self, node: str) -> None:
        try:
            self.store.delete(DeviceTaintRule, node_quarantine_name(node))
        except NotFoundError:
            pass

    def node_quarantined(self, node: str) -> bool:
        return node_quarantined(self.store, node)

    def claimable(self, node: str) -> List[SliceDevice]:
        """What a scheduler could still place on: published and untainted.
        (Used by tests' scheduler simulation and the syncer's accounting.)"""
        sl = self.store.try_get(ResourceSlice, slice_object_name(node))
        if sl is None:
            return []
        return [d for d in sl.spec.devices if not self.tainted(d.uuid)]
