"""RemoteNodeAgent — client side of the node-agent RPC seam.

Implements the NodeAgent interface by routing each call to the target node's
agent service (serve.py), resolving endpoints from ``Node.spec.agent_endpoint``
in the store. This replaces the reference's controller→node transport
(pods/exec SPDY + chroot, utils/gpus.go:996-1067) with a typed HTTP seam.

Error mapping mirrors the server: 409/kind=busy → DeviceBusyError (the
open-fd drain guard), other agent failures → AgentError, transport failures →
AgentError (a dead agent reads the same as a dead node, which is what the
controllers' GC paths expect).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Callable, List, Optional

from tpu_composer.agent import cdi as cdimod
from tpu_composer.agent.nodeagent import (
    MAX_WATCH_S,
    AgentError,
    DeviceBusyError,
    NodeAgent,
)
from tpu_composer.agent.serve import spec_to_wire


class RemoteNodeAgent(NodeAgent):
    def __init__(
        self,
        resolver: Callable[[str], str],
        timeout: float = 30.0,
    ) -> None:
        """``resolver(node) -> "host:port"`` of that node's agent service."""
        self._resolve = resolver
        self.timeout = timeout

    @classmethod
    def from_store(
        cls,
        store,
        timeout: float = 30.0,
        endpoint_template: str = "",
    ) -> "RemoteNodeAgent":
        """Resolve endpoints from ``Node.spec.agent_endpoint``, falling back
        to ``endpoint_template`` (e.g. ``{node}:9444``, the node-agent
        DaemonSet's hostPort) for nodes that never registered one —
        NODE_AGENT_ENDPOINT_TEMPLATE in deploy/manager.yaml."""
        from tpu_composer.api.types import Node

        def resolver(node: str) -> str:
            obj = store.try_get(Node, node)
            if obj is not None and obj.spec.agent_endpoint:
                return obj.spec.agent_endpoint
            if endpoint_template:
                return endpoint_template.format(node=node)
            raise AgentError(f"node {node}: no agent endpoint registered")

        return cls(resolver, timeout=timeout)

    # ------------------------------------------------------------------
    def _call(self, node: str, method: str, _transport_timeout=None, **args):
        endpoint = self._resolve(node)
        url = f"http://{endpoint}/v1/{method}"
        body = json.dumps({"node": node, **args}).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=_transport_timeout or self.timeout
            ) as resp:
                return json.loads(resp.read()).get("result")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {}
            message = payload.get("error", f"HTTP {e.code}")
            if payload.get("kind") == "busy":
                raise DeviceBusyError(message) from e
            raise AgentError(f"{node} agent: {message}") from e
        except (urllib.error.URLError, OSError) as e:
            raise AgentError(f"{node} agent unreachable at {endpoint}: {e}") from e

    # -- NodeAgent interface -----------------------------------------------
    def ensure_driver(self, node: str) -> str:
        return self._call(node, "ensure_driver")

    def check_visible(self, node: str, device_ids: List[str], group: str = "") -> bool:
        return bool(
            self._call(node, "check_visible", device_ids=device_ids, group=group)
        )

    def check_no_loads(self, node: str, device_ids: List[str], group: str = "") -> bool:
        return bool(
            self._call(node, "check_no_loads", device_ids=device_ids, group=group)
        )

    def drain(self, node: str, device_ids: List[str], force: bool = False,
              group: str = "") -> None:
        self._call(node, "drain", device_ids=device_ids, force=force, group=group)

    def refresh_device_stack(
        self,
        node: str,
        spec: Optional[cdimod.CdiSpec] = None,
        remove_name: str = "",
    ) -> None:
        self._call(
            node,
            "refresh_device_stack",
            spec=spec_to_wire(spec) if spec is not None else None,
            remove_name=remove_name,
        )

    def create_device_taint(self, node: str, device_ids: List[str], reason: str) -> None:
        self._call(node, "create_device_taint", device_ids=device_ids, reason=reason)

    def delete_device_taint(self, node: str, device_ids: List[str]) -> None:
        self._call(node, "delete_device_taint", device_ids=device_ids)

    def has_device_taint(self, node: str, device_id: str) -> bool:
        return bool(self._call(node, "has_device_taint", device_id=device_id))

    def wait_device_event(self, node: str, timeout: float = 1.0) -> bool:
        """Long-poll the node's /dev watch. A per-node DeviceEventWatcher
        wraps this for event-driven reconciles in cluster mode. The timeout
        is clamped to the shared MAX_WATCH_S cap the server enforces — a
        larger request would silently become unwatched sleep on this side —
        and the transport timeout is padded to outlive the server-side
        wait."""
        timeout = min(max(0.0, timeout), MAX_WATCH_S)
        return bool(self._call(node, "wait_device_event", timeout=timeout,
                               _transport_timeout=timeout + 5.0))
