"""Node-agent RPC service — the controller↔node transport.

The reference actuates nodes by `pods/exec` (SPDY) into privileged pods and
shelling nvidia-smi/modprobe (utils/gpus.go:1040-1067). Our node agent is a
small HTTP service running on each node (the DaemonSet in
deploy/node-agent.yaml) exposing the NodeAgent interface as JSON POSTs:

    POST /v1/<method>   {args...} -> {"result": ...} | {"error","kind"}
    GET  /healthz

The wire protocol is deliberately dumb — one POST per NodeAgent method, all
idempotent, no streaming — so the seam stays as testable as the in-process
interface (SURVEY.md §4: prefer DI seams over exec interception).
``RemoteNodeAgent`` (remote.py) is the client side.

Run on a node: ``python -m tpu_composer.agent.serve --bind 0.0.0.0:9444``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_composer.agent import cdi as cdimod
from tpu_composer.agent.nodeagent import (
    MAX_WATCH_S,
    AgentError,
    DeviceBusyError,
    LocalNodeAgent,
    NodeAgent,
)

# Methods exposed over the wire; each maps 1:1 onto NodeAgent.
_METHODS = frozenset(
    {
        "ensure_driver",
        "check_visible",
        "check_no_loads",
        "drain",
        "refresh_device_stack",
        "create_device_taint",
        "delete_device_taint",
        "has_device_taint",
        "wait_device_event",
    }
)


def spec_to_wire(spec: cdimod.CdiSpec) -> dict:
    return {
        "name": spec.name,
        "device_nodes": list(spec.device_nodes),
        "env": dict(spec.env),
        "libtpu_host_path": spec.libtpu_host_path,
    }


def spec_from_wire(d: dict) -> cdimod.CdiSpec:
    return cdimod.CdiSpec(
        name=d["name"],
        device_nodes=list(d.get("device_nodes", [])),
        env=dict(d.get("env", {})),
        libtpu_host_path=d.get("libtpu_host_path", cdimod.DEFAULT_LIBTPU_PATH),
    )


class AgentServer:
    """Serves one NodeAgent over HTTP (one instance per node)."""

    def __init__(self, agent: NodeAgent, bind: str = "127.0.0.1:0") -> None:
        self.agent = agent
        host, _, port = bind.rpartition(":")
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    self._send(200, {"ok": True})
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                if not self.path.startswith("/v1/"):
                    return self._send(404, {"error": f"no route {self.path}"})
                method = self.path[len("/v1/"):]
                if method not in _METHODS:
                    return self._send(404, {"error": f"unknown method {method}"})
                length = int(self.headers.get("Content-Length") or 0)
                try:
                    args = json.loads(self.rfile.read(length)) if length else {}
                except ValueError:
                    return self._send(400, {"error": "bad JSON body"})
                try:
                    result = server._call(method, args)
                except DeviceBusyError as e:
                    return self._send(409, {"error": str(e), "kind": "busy"})
                except AgentError as e:
                    return self._send(500, {"error": str(e), "kind": "agent"})
                self._send(200, {"result": result})

            def _send(self, code: int, payload: dict) -> None:
                data = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), Handler)
        self._thread: Optional[threading.Thread] = None

    def _call(self, method: str, args: dict):
        node = args.get("node", "")
        if method == "ensure_driver":
            return self.agent.ensure_driver(node)
        if method == "check_visible":
            return self.agent.check_visible(
                node, list(args.get("device_ids", [])), group=args.get("group", "")
            )
        if method == "check_no_loads":
            return self.agent.check_no_loads(
                node, list(args.get("device_ids", [])), group=args.get("group", "")
            )
        if method == "drain":
            self.agent.drain(
                node,
                list(args.get("device_ids", [])),
                force=bool(args.get("force", False)),
                group=args.get("group", ""),
            )
            return True
        if method == "refresh_device_stack":
            spec = args.get("spec")
            self.agent.refresh_device_stack(
                node,
                spec=spec_from_wire(spec) if spec else None,
                remove_name=args.get("remove_name", ""),
            )
            return True
        if method == "create_device_taint":
            self.agent.create_device_taint(
                node, list(args.get("device_ids", [])), args.get("reason", "")
            )
            return True
        if method == "delete_device_taint":
            self.agent.delete_device_taint(node, list(args.get("device_ids", [])))
            return True
        if method == "has_device_taint":
            return self.agent.has_device_taint(node, args.get("device_id", ""))
        if method == "wait_device_event":
            # Long-poll: blocks this handler thread (ThreadingHTTPServer) up
            # to the capped timeout. Agents without a watch capability
            # (NodeAgent's default) answer False so callers degrade to
            # polling — the DeviceEventWatcher throttles that fast-False.
            try:
                timeout = min(max(0.0, float(args.get("timeout", 1.0))),
                              MAX_WATCH_S)
            except (TypeError, ValueError) as e:
                raise AgentError(f"bad wait_device_event timeout: {e}") from e
            return bool(self.agent.wait_device_event(node, timeout=timeout))
        raise AgentError(f"unhandled method {method}")  # pragma: no cover

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="node-agent", daemon=True
        )
        self._thread.start()

    def serve_forever(self) -> None:  # pragma: no cover - CLI path
        self._httpd.serve_forever()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


def main(argv=None) -> int:  # pragma: no cover - CLI path
    p = argparse.ArgumentParser(prog="tpu-composer-node-agent")
    p.add_argument("--bind", default="0.0.0.0:9444")
    p.add_argument("--dev-dir", default="/dev")
    p.add_argument("--proc-dir", default="/host-proc")
    p.add_argument("--cdi-dir", default=cdimod.DEFAULT_CDI_DIR)
    p.add_argument("--state-dir", default="/var/run/tpu-composer")
    p.add_argument(
        "--device-plugin-dir",
        default=os.environ.get("DEVICE_PLUGIN_DIR", ""),
        help="kubelet device-plugin dir (e.g. /var/lib/kubelet/device-plugins);"
             " empty disables the device plugin",
    )
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    agent = LocalNodeAgent(
        dev_dir=args.dev_dir,
        proc_dir=args.proc_dir,
        cdi_dir=args.cdi_dir,
        state_dir=args.state_dir,
    )
    server = AgentServer(agent, bind=args.bind)
    logging.getLogger("node-agent").info("serving on %s", server.address)
    if args.device_plugin_dir:
        # Composed chips become a schedulable extended resource straight from
        # this agent's CDI claim state (agent/plugin.py); the operator's
        # attach/detach RPCs land in refresh_device_stack, whose claims the
        # lister reads, so ListAndWatch pushes follow automatically.
        from tpu_composer.agent.plugin import TPUDevicePlugin, lister_from_agent

        plugin = TPUDevicePlugin(
            lister_from_agent(agent),
            args.device_plugin_dir,
            node_name=os.environ.get("NODE_NAME", ""),
        )
        plugin.start()
        try:
            plugin.register_with_kubelet()
        except Exception as e:  # kubelet may not be up yet; it re-dials plugins
            logging.getLogger("node-agent").warning(
                "kubelet registration failed (will rely on kubelet restart): %s", e
            )
    server.serve_forever()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
