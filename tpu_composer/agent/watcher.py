"""Device event watchers — event-driven reconcile nudges from /dev changes.

The reference detects device visibility only by re-running its checks on a
fixed 30s requeue (composableresource_controller.go:298) — the dominant term
in its attach-to-Ready latency (BASELINE.md). These runnables invert that:
they block in the node agent's ``wait_device_event`` (inotify via
native/tpunode.cc's ``tpun_watch_dev`` locally; HTTP long-poll via
serve.py/remote.py in cluster mode) and, the instant a device node appears
or vanishes, enqueue every non-terminal ComposableResource on the affected
host so the controller re-checks visibility immediately.

Polling requeues stay in place as the safety net; the watchers just make the
happy path latency-bound by the fabric, not by a poll quantum.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from tpu_composer.api.types import (
    ComposableResource,
    Node,
    RESOURCE_STATE_DELETING,
)
from tpu_composer.runtime.controller import Controller


class DeviceEventWatcher:
    """Manager runnable: device-node churn -> resource-controller enqueues.

    ``node_name`` scopes both the agent call and the nudges to one host
    (empty nudges every non-terminal resource). ``should_run`` lets an owner
    (MultiNodeWatcher) retire this watcher when its node leaves the cluster.
    """

    def __init__(
        self,
        agent,  # NodeAgent: wait_device_event(node, timeout) -> bool
        controller: Controller,
        node_name: str = "",
        wait_timeout: float = 1.0,
        should_run: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.agent = agent
        self.controller = controller
        self.node_name = node_name
        self.wait_timeout = wait_timeout
        self.should_run = should_run
        self.log = logging.getLogger("DeviceEventWatcher")
        self.events_seen = 0

    def _targets(self):
        out = []
        for res in self.controller.store.list(ComposableResource):
            if res.status.state == RESOURCE_STATE_DELETING:
                continue
            if self.node_name and res.spec.target_node != self.node_name:
                continue
            out.append(res.metadata.name)
        return out

    def nudge(self) -> int:
        """Enqueue all candidate resources; returns how many."""
        names = self._targets()
        for name in names:
            self.controller.queue.add(name)
        return len(names)

    def __call__(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            if self.should_run is not None and not self.should_run():
                return
            started = time.monotonic()
            fired = False
            try:
                fired = self.agent.wait_device_event(self.node_name,
                                                     timeout=self.wait_timeout)
                if fired:
                    self.events_seen += 1
                    n = self.nudge()
                    self.log.debug("device event -> nudged %d resource(s)", n)
            except Exception as e:  # watcher must never kill the manager
                self.log.warning("device watch on %r failed: %s",
                                 self.node_name, e)
            if fired:
                # Re-arm the watch immediately: device attaches arrive in
                # bursts (one inotify event per chip of a group), and the
                # per-call watch is torn down between waits.
                continue
            # Throttle: an agent without watch capability answers False
            # immediately (NodeAgent's default) — sleep out the remainder of
            # the window instead of spinning an unthrottled poll/RPC loop.
            remainder = self.wait_timeout - (time.monotonic() - started)
            if remainder > 0 and stop_event.wait(remainder):
                return


class MultiNodeWatcher:
    """Cluster-mode runnable: one DeviceEventWatcher thread per Node in the
    store, long-polling that node's agent (RemoteNodeAgent -> serve.py).
    Rescans the node list every ``refresh`` seconds, starting watchers for
    new nodes and retiring watchers whose node is gone."""

    def __init__(
        self,
        agent,
        controller: Controller,
        wait_timeout: float = 5.0,
        refresh: float = 10.0,
    ) -> None:
        self.agent = agent
        self.controller = controller
        self.wait_timeout = wait_timeout
        self.refresh = refresh
        self.log = logging.getLogger("MultiNodeWatcher")
        self._live: set = set()  # node names with an active watcher

    def _nodes(self) -> set:
        return {n.metadata.name for n in self.controller.store.list(Node)}

    def __call__(self, stop_event: threading.Event) -> None:
        threads = {}
        while not stop_event.is_set():
            current = self._nodes()
            self._live = current
            for node in current - set(threads):
                w = DeviceEventWatcher(
                    self.agent, self.controller, node_name=node,
                    wait_timeout=self.wait_timeout,
                    should_run=lambda n=node: n in self._live,
                )
                t = threading.Thread(target=w, args=(stop_event,),
                                     name=f"dev-watch-{node}", daemon=True)
                t.start()
                threads[node] = t
            for node, t in list(threads.items()):
                if not t.is_alive():
                    del threads[node]
            if stop_event.wait(self.refresh):
                break
        for t in threads.values():
            t.join(timeout=self.wait_timeout + 1.0)
