"""Invariant analyzer suite: tpuc-lint AST passes + the lockdep witness.

Twelve PRs of concurrency machinery rest on invariants that used to live
only in review comments: fence-checked fabric mutation paths (PR 8), the
Attaching/Detaching intent protocol (PR 5), observation-clock discipline
in lease logic (PR 8), named threads for profiler attribution (PR 10),
and the env-knob / metric documentation contract (OPERATIONS.md). This
package makes each of them machine-checked:

- ``tpuc-lint`` (``python -m tpu_composer.analysis`` / ``make analyze``):
  an AST-walking pass framework (core.py) with one pass per invariant
  (passes/), each proven by a known-bad fixture under
  ``tests/analysis_fixtures/``.
- ``lockdep`` (lockdep.py): a runtime lock-order witness fed by
  ``ObservedLock`` (runtime/contention.py). Per-thread held-lock stacks
  feed a global acquisition-order graph; a cycle is a potential ABBA
  deadlock (the PR 3 store-lock/informer-start shape) and raises in
  tests. Enabled suite-wide via tests/conftest.py so tier-1 doubles as a
  standing deadlock detector.
"""

from tpu_composer.analysis.core import (  # noqa: F401
    LintFile,
    Pass,
    Violation,
    run_passes,
)


def all_passes():
    """The registered pass list (imported lazily so ``lockdep`` users
    never pay for the AST machinery)."""
    from tpu_composer.analysis.passes import PASSES

    return list(PASSES)
