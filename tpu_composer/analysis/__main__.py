"""tpuc-lint CLI: ``python -m tpu_composer.analysis`` (make analyze).

Exit status: 0 clean, 1 violations, 2 usage error. Default scope is the
whole ``tpu_composer`` package plus ``bench.py``; ``--paths`` narrows to
explicit files/dirs (the fixture tests use this). ``--json`` emits one
object per violation for tooling; the human format is
``path:line: [pass-id] message`` with the invariant cited underneath.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from tpu_composer.analysis import all_passes
from tpu_composer.analysis.core import run_passes


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_composer.analysis",
        description="tpuc-lint: repo-invariant AST passes",
    )
    parser.add_argument(
        "--list", action="store_true", help="list passes and exit"
    )
    parser.add_argument(
        "--pass",
        dest="only",
        action="append",
        metavar="PASS_ID",
        help="run only this pass (repeatable)",
    )
    parser.add_argument(
        "--paths",
        nargs="+",
        metavar="PATH",
        help="lint these files/dirs instead of the default scope",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    args = parser.parse_args(argv)

    passes = all_passes()
    if args.list:
        for p in passes:
            print(f"{p.id}: {p.invariant}")
        return 0
    if args.only:
        known = {p.id for p in passes}
        unknown = [pid for pid in args.only if pid not in known]
        if unknown:
            print(
                f"unknown pass id(s): {', '.join(unknown)}"
                f" (known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2
        passes = [p for p in passes if p.id in args.only]

    violations = run_passes(passes, paths=args.paths)
    if args.json:
        for v in violations:
            print(
                json.dumps(
                    {
                        "pass": v.pass_id,
                        "path": v.path,
                        "line": v.line,
                        "message": v.message,
                        "invariant": v.invariant,
                    }
                )
            )
    else:
        for v in violations:
            print(v.format())
            print(f"    invariant: {v.invariant}")
        summary = (
            f"tpuc-lint: {len(violations)} violation(s) across"
            f" {len(passes)} pass(es)"
        )
        print(summary if violations else f"{summary} — clean")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
