"""tpuc-lint pass framework: parsed-file model, suppressions, runner.

Each pass is a small class with an ``id``, a one-line ``invariant`` (the
thing the repo already paid for — cited in every violation so the fix
commit can name its reviewer), and a ``check(file) -> [Violation]``. The
runner parses every in-scope source file ONCE into a :class:`LintFile`
(source + AST + per-line suppressions) and hands the same object to all
passes, so a full-tree run costs one parse per file.

Suppression syntax (documented in docs/OPERATIONS.md):

- line level: a trailing ``# tpuc: ignore[pass-id]`` comment silences
  that pass for violations anchored on that line (or the statement
  starting there). ``# tpuc: ignore[pass-a,pass-b]`` silences several.
- file level: ``# tpuc: ignore-file[pass-id]`` anywhere in the first 10
  lines opts the whole file out of one pass — for designated-exception
  modules (e.g. the cold-start adoption pass mutates fabric directly
  because it runs before any controller or shard fence exists).

Suppressions are deliberately per-pass (never bare ``# tpuc: ignore``):
an untargeted escape hatch rots into "ignore everything".
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set

_SUPPRESS_RE = re.compile(r"#\s*tpuc:\s*ignore\[([a-z0-9_,\- ]+)\]")
_SUPPRESS_FILE_RE = re.compile(r"#\s*tpuc:\s*ignore-file\[([a-z0-9_,\- ]+)\]")
_FILE_SUPPRESS_WINDOW = 10  # ignore-file must sit in the first N lines


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant breach, anchored to a file:line."""

    pass_id: str
    path: str  # repo-relative
    line: int
    message: str
    invariant: str  # the one-line invariant the pass encodes

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class LintFile:
    """One parsed source file shared by every pass."""

    def __init__(self, path: str, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=path)
        except SyntaxError as e:  # surfaced as its own violation by run_passes
            self.parse_error = e
        self._line_suppress: Dict[int, Set[str]] = {}
        self._file_suppress: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self._line_suppress.setdefault(i, set()).update(ids)
            if i <= _FILE_SUPPRESS_WINDOW:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self._file_suppress.update(
                        p.strip() for p in m.group(1).split(",") if p.strip()
                    )

    def suppressed(self, pass_id: str, line: int) -> bool:
        if pass_id in self._file_suppress:
            return True
        return pass_id in self._line_suppress.get(line, set())


class Pass:
    """Base class: subclasses set ``id``/``invariant`` and implement
    ``check``. ``check`` yields raw findings; the runner applies
    suppressions, so passes never reason about them."""

    id: str = ""
    invariant: str = ""

    def check(self, file: LintFile) -> Iterable[Violation]:
        raise NotImplementedError

    # Helper so passes build violations without repeating their identity.
    def violation(self, file: LintFile, line: int, message: str) -> Violation:
        return Violation(
            pass_id=self.id,
            path=file.rel,
            line=line,
            message=message,
            invariant=self.invariant,
        )


def repo_root() -> str:
    """The repo checkout root: the directory holding the ``tpu_composer``
    package (needed because the doc-drift passes read docs/ and
    cmd/main.py relative to it)."""
    here = os.path.dirname(os.path.abspath(__file__))  # .../tpu_composer/analysis
    return os.path.dirname(os.path.dirname(here))


_SKIP_DIRS = {"__pycache__"}


def discover_files(
    root: Optional[str] = None, paths: Optional[Sequence[str]] = None
) -> List[LintFile]:
    """Build :class:`LintFile` objects for the analysis scope.

    Default scope is every ``.py`` under ``tpu_composer/`` plus
    ``bench.py`` — tests/ is deliberately out (it holds the known-bad
    fixtures that must keep failing the passes). ``paths`` overrides the
    scope with explicit files/directories (the fixture tests use this).
    """
    root = root or repo_root()
    files: List[LintFile] = []
    if paths is None:
        targets: List[str] = [os.path.join(root, "tpu_composer")]
        bench = os.path.join(root, "bench.py")
        if os.path.exists(bench):
            targets.append(bench)
    else:
        targets = [
            p if os.path.isabs(p) else os.path.join(root, p) for p in paths
        ]
    seen: Set[str] = set()
    for target in targets:
        if os.path.isfile(target):
            _add_file(files, seen, target, root)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    _add_file(files, seen, os.path.join(dirpath, fn), root)
    return files


def _add_file(files: List[LintFile], seen: Set[str], path: str, root: str) -> None:
    path = os.path.abspath(path)
    if path in seen:
        return
    seen.add(path)
    rel = os.path.relpath(path, root)
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    files.append(LintFile(path, rel, source))


def run_passes(
    passes: Sequence[Pass],
    root: Optional[str] = None,
    paths: Optional[Sequence[str]] = None,
    files: Optional[Sequence[LintFile]] = None,
) -> List[Violation]:
    """Run ``passes`` over the scope; returns suppression-filtered
    violations sorted by (path, line, pass)."""
    if files is None:
        files = discover_files(root=root, paths=paths)
    out: List[Violation] = []
    for f in files:
        if f.parse_error is not None:
            out.append(
                Violation(
                    pass_id="parse",
                    path=f.rel,
                    line=f.parse_error.lineno or 1,
                    message=f"syntax error: {f.parse_error.msg}",
                    invariant="source files must parse",
                )
            )
            continue
        for p in passes:
            for v in p.check(f):
                if not f.suppressed(v.pass_id, v.line):
                    out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.pass_id))
    return out


# -- shared AST helpers used by several passes ---------------------------


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``a.b.c(...)`` -> ``"a.b.c"``;
    empty string when the receiver chain is not plain names/attributes
    (subscripts, calls, etc.)."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def functions(tree: ast.AST) -> List[ast.AST]:
    """Every function/method definition in the module, including nested."""
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def string_constants(tree: ast.AST) -> List[ast.Constant]:
    """Every string-literal Constant that is NOT a docstring/bare
    expression statement (so prose mentions never count as references)."""
    docstring_ids = set()
    for n in ast.walk(tree):
        body = getattr(n, "body", None)
        if isinstance(body, list):
            for stmt in body:
                if isinstance(stmt, ast.Expr) and isinstance(
                    stmt.value, ast.Constant
                ):
                    docstring_ids.add(id(stmt.value))
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, ast.Constant)
        and isinstance(n.value, str)
        and id(n) not in docstring_ids
    ]
