"""lockdep: a runtime lock-acquisition-order witness for ObservedLock.

The PR 3 review caught a real ABBA deadlock by hand: admission hooks
holding the Store lock read through the informer cache, while a lazy
informer start holding the cache lock listed through the store. This
module is the machine that catches the next one: every ``ObservedLock``
acquire/release (runtime/contention.py) feeds a per-thread held-lock
stack, and each "acquire B while holding A" observation adds the edge
A→B to a global acquisition-order graph. Lock ORDER must be globally
consistent — the first B→A observation that closes a cycle is a
potential ABBA deadlock even if the two threads never actually collided
in this run, and the witness reports it with both acquisition stacks.

Semantics (mirroring the kernel's lockdep where it translates):

- **Lock classes, not instances.** Edges are keyed by lock NAME (the
  ObservedLock name = the lock's class: ``store``, ``informer:<kind>``,
  ``dispatcher``, ...). Two Store instances in a two-replica test share
  the class ``store``.
- **Same-class nesting is not a cycle.** Holding instance A of class
  ``store`` while acquiring instance B of the same class would render as
  a self-edge; without subclass annotations that is noise (the
  two-replica harnesses do this legitimately), so self-edges are counted
  (``nested_same_class``) but never treated as cycles. A DIFFERENT pair
  of classes closing a loop always is.
- **Cond-parks release.** ``Condition.wait`` really releases the lock:
  ``_release_save`` pops it from the held stack, ``_acquire_restore``
  re-pushes WITHOUT recording edges — the order was established at the
  original acquire, and a wakeup re-acquire is not a new ordering
  decision.
- **Reentrancy is free.** Only the outermost acquire of an RLock is an
  ordering event; contention.py already filters inner re-acquires.
- **Declared order.** ``declare_order(earlier, later)`` pins an edge
  direction a priori (the store/informer order the PR 3 fix
  established); a later observation of the REVERSED edge raises
  immediately even before any cycle exists.

Modes: the witness raises :class:`LockOrderViolation` at the offending
acquire when ``strict`` (the test-suite default — the stack that closed
the cycle is the bug's address), or records the report for teardown when
not. Either way every cycle lands in ``reports`` for the conftest
session summary and the ``TPUC_LOCKDEP_FILE`` artifact.

Enabled via ``TPUC_LOCKDEP=1`` (``--lockdep`` on the operator, conftest
for the suite); the disabled path costs ObservedLock one module-global
``is None`` check per outermost acquire.
"""

from __future__ import annotations

import json
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """Raised (strict mode) at the acquire that closed an order cycle or
    contradicted a declared order."""


class _Edge:
    """First-observation evidence for one ordered pair (a held while b
    acquired)."""

    __slots__ = ("held", "acquired", "thread", "stack", "count")

    def __init__(self, held: str, acquired: str, thread: str, stack: str) -> None:
        self.held = held
        self.acquired = acquired
        self.thread = thread
        self.stack = stack
        self.count = 1

    def summary(self) -> Dict[str, object]:
        return {
            "held": self.held,
            "acquired": self.acquired,
            "thread": self.thread,
            "count": self.count,
            "stack": self.stack,
        }


class LockdepWitness:
    """One acquisition-order graph. The module-level singleton is what
    ObservedLock feeds; standalone instances back the unit tests and the
    ABBA regression fixture (so a deliberately-poisoned graph never
    leaks into the suite-wide witness)."""

    def __init__(self, strict: bool = True, stack_depth: int = 12) -> None:
        self.strict = strict
        self.stack_depth = stack_depth
        self._lock = threading.Lock()
        #: adjacency: held-class -> {acquired-class}
        self._succ: Dict[str, Set[str]] = {}
        self._edges: Dict[Tuple[str, str], _Edge] = {}
        self._declared: List[Tuple[str, str]] = []  # (earlier, later)
        # Lock classes seen at ANY acquire (not just edge-forming ones) so
        # /debug/lockdep shows coverage on an idle operator. A dict with
        # GIL-atomic setitem: the hot no-locks-held acquire path must not
        # take the witness lock.
        self._classes: Dict[str, bool] = {}
        self._reported: Set[Tuple[str, str]] = set()  # deduped closing edges
        self.nested_same_class = 0
        self.reports: List[Dict[str, object]] = []

    # -- declared order ------------------------------------------------
    def declare_order(self, earlier: str, later: str) -> None:
        """Pin ``earlier`` strictly before ``later``: observing ``later``
        held while ``earlier`` is acquired is a violation on first sight,
        cycle or not. A trailing ``*`` matches a class-name prefix
        (``informer:*`` covers every per-kind informer lock)."""
        with self._lock:
            self._declared.append((earlier, later))

    @staticmethod
    def _match(pattern: str, name: str) -> bool:
        if pattern.endswith("*"):
            return name.startswith(pattern[:-1])
        return name == pattern

    def _declared_forbids(self, held: str, acquiring: str) -> Optional[str]:
        """Non-None (the declaration text) when acquiring ``acquiring``
        while holding ``held`` contradicts a declared order."""
        for earlier, later in self._declared:
            if self._match(earlier, acquiring) and self._match(later, held):
                return f"{earlier} strictly before {later}"
        return None

    # -- hot-path hooks (called by ObservedLock) -----------------------
    def held_stack(self) -> List[Tuple[str, int]]:
        """The held stack is MODULE-global, not per-witness: which locks
        a thread physically holds is process truth. If each witness kept
        its own, a scoped_witness swap while a background thread held an
        ObservedLock would strand the push in the old witness (the
        release inside the scope pops the new one), and the stale entry
        would fabricate edges — spurious strict violations in unrelated
        later tests."""
        stack = getattr(_held_tls, "held", None)
        if stack is None:
            stack = _held_tls.held = []
        return stack

    def note_acquire(self, name: str, instance_id: int) -> None:
        """Record ordering edges for acquiring ``name`` while holding the
        current stack. Called BEFORE blocking on the inner lock: the
        ordering decision is made at the attempt, and recording it even
        for uncontended acquires is what lets the witness flag a cycle no
        actual collision exercised."""
        self._classes[name] = True  # GIL-atomic; no witness lock needed
        held = self.held_stack()
        if held:
            self._observe(held, name, instance_id)
        held.append((name, instance_id))

    def note_acquire_failed(self, name: str) -> None:
        """A non-blocking/timed acquire failed: undo the speculative
        push (edges stay — the ordering ATTEMPT happened)."""
        held = self.held_stack()
        if held and held[-1][0] == name:
            held.pop()

    def note_release(self, name: str) -> None:
        held = self.held_stack()
        # Out-of-order releases are legal (lock A then B, release A then
        # B): remove the most recent matching entry.
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == name:
                del held[i]
                return

    def note_park(self, name: str) -> None:
        """Condition.wait released the lock for the park's duration."""
        self.note_release(name)

    def note_unpark(self, name: str, instance_id: int) -> None:
        """Wakeup re-acquired the cond lock. Deliberately NOT an ordering
        event (see module docstring) — just restore the held stack."""
        self.held_stack().append((name, instance_id))

    # -- graph ---------------------------------------------------------
    def _observe(
        self, held: List[Tuple[str, int]], name: str, instance_id: int
    ) -> None:
        thread = threading.current_thread().name
        new_reports = []
        with self._lock:
            for held_name, held_id in held:
                if held_name == name:
                    if held_id != instance_id:
                        self.nested_same_class += 1
                    continue  # same-class nesting: counted, never a cycle
                key = (held_name, name)
                edge = self._edges.get(key)
                if edge is not None:
                    edge.count += 1
                    continue
                if key in self._reported:
                    continue  # this bad edge already produced a report
                stack = "".join(
                    traceback.format_stack(limit=self.stack_depth)[:-2]
                )
                declared = self._declared_forbids(held_name, name)
                if declared is not None:
                    report = self._declared_violation_report(
                        held_name, name, declared, thread, stack
                    )
                    self._reported.add(key)
                    self.reports.append(report)
                    new_reports.append(report)
                    continue  # don't poison the graph with the bad edge
                path = self._path(name, held_name)
                if path is not None:
                    report = self._cycle_report(
                        held_name, name, path, thread, stack
                    )
                    self._reported.add(key)
                    self.reports.append(report)
                    new_reports.append(report)
                    continue  # keep the graph acyclic: reject the edge
                self._edges[key] = _Edge(held_name, name, thread, stack)
                self._succ.setdefault(held_name, set()).add(name)
        if new_reports and self.strict:
            raise LockOrderViolation(format_report(new_reports[0]))

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """A path src -> ... -> dst in the order graph, or None."""
        if src == dst:
            return [src]
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            for nxt in self._succ.get(node, ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _cycle_report(
        self,
        held: str,
        acquired: str,
        path: List[str],
        thread: str,
        stack: str,
    ) -> Dict[str, object]:
        # path runs acquired -> ... -> held through existing edges; the
        # new held->acquired edge closes the loop back to the start.
        cycle = path + [acquired]
        evidence = []
        for a, b in zip(path, path[1:]):
            edge = self._edges.get((a, b))
            if edge is not None:
                evidence.append(edge.summary())
        return {
            "kind": "cycle",
            "closing_edge": {"held": held, "acquired": acquired},
            "cycle": cycle,
            "thread": thread,
            "stack": stack,
            "evidence": evidence,
        }

    def _declared_violation_report(
        self, held: str, acquired: str, declared: str, thread: str, stack: str
    ) -> Dict[str, object]:
        return {
            "kind": "declared-order",
            "closing_edge": {"held": held, "acquired": acquired},
            "declared": declared,
            "thread": thread,
            "stack": stack,
            "evidence": [],
        }

    # -- reporting -----------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "classes": sorted(list(self._classes)),
                "edges": [e.summary() for e in self._edges.values()],
                "declared": [
                    {"earlier": a, "later": b} for a, b in self._declared
                ],
                "nested_same_class": self.nested_same_class,
                "reports": list(self.reports),
            }

    def dump(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.snapshot(), f, indent=2, sort_keys=True)


def format_report(report: Dict[str, object]) -> str:
    edge = report["closing_edge"]
    if report["kind"] == "declared-order":
        head = (
            f"lockdep: declared-order violation — acquired"
            f" '{edge['acquired']}' while holding '{edge['held']}'"
            f" (declared: {report['declared']})"
        )
    else:
        head = (
            "lockdep: potential ABBA deadlock — acquiring"
            f" '{edge['acquired']}' while holding '{edge['held']}' closes"
            f" the cycle {' -> '.join(report['cycle'])}"
        )
    lines = [head, f"  offending thread: {report['thread']}"]
    stack = str(report.get("stack", "")).rstrip()
    if stack:
        lines.append("  acquisition stack:")
        lines.extend("    " + ln for ln in stack.splitlines())
    for ev in report.get("evidence", []):
        lines.append(
            f"  prior edge {ev['held']} -> {ev['acquired']} first seen on"
            f" thread {ev['thread']} (x{ev['count']}):"
        )
        lines.extend(
            "    " + ln for ln in str(ev["stack"]).rstrip().splitlines()
        )
    return "\n".join(lines)


# -- module-level witness (what ObservedLock feeds) ----------------------

#: Per-thread held-lock stacks — shared by every witness (see
#: LockdepWitness.held_stack for why).
_held_tls = threading.local()

_witness: Optional[LockdepWitness] = None
_witness_lock = threading.Lock()


def enable(strict: bool = True) -> LockdepWitness:
    """Install (or return) the process-wide witness. Idempotent; the
    strict flag of the FIRST enable wins for an existing witness."""
    global _witness
    with _witness_lock:
        if _witness is None:
            _witness = LockdepWitness(strict=strict)
            _declare_default_order(_witness)
        return _witness


def disable() -> None:
    global _witness
    with _witness_lock:
        _witness = None


def current() -> Optional[LockdepWitness]:
    return _witness


class scoped_witness:
    """Swap in a fresh witness for a ``with`` block — the ABBA regression
    fixture deliberately poisons its graph, which must never leak into
    the suite-wide one."""

    def __init__(self, strict: bool = True) -> None:
        self.witness = LockdepWitness(strict=strict)
        self._prev: Optional[LockdepWitness] = None

    def __enter__(self) -> LockdepWitness:
        global _witness
        with _witness_lock:
            self._prev = _witness
            _witness = self.witness
        return self.witness

    def __exit__(self, *exc) -> None:
        global _witness
        with _witness_lock:
            _witness = self._prev


def dump_file() -> None:
    """Env-gated artifact write (``$TPUC_LOCKDEP_FILE``) for the
    crash/black-box hooks and the conftest teardown; no-op without an
    active witness or a configured path. Never raises (callers are exit
    paths)."""
    import os

    path = os.environ.get("TPUC_LOCKDEP_FILE", "")
    w = _witness
    if not path or w is None:
        return
    try:
        w.dump(path)
    except OSError:
        pass


def _declare_default_order(w: LockdepWitness) -> None:
    """The one order the repo has already paid to learn (the PR 3 ABBA
    fix): informer locks nest INSIDE the store lock — the store's
    admission hooks may read through the cache, so an informer lock must
    never be held while the store lock is acquired. Declared for every
    informer class the cache constructs (names are per-kind)."""
    w.declare_order("store", "informer:*")
