"""tpuc-lint pass registry: one pass per invariant the repo paid for.

Adding a pass: implement it in a module here, append an instance to
``PASSES``, add a known-bad + fixed fixture pair under
``tests/analysis_fixtures/<pass-id>/`` and a proof in
tests/test_analysis.py that the pass fails on the bad form and accepts
the fixed form. A pass without a failing fixture is not proven to check
anything.
"""

from tpu_composer.analysis.passes.docs_drift import (
    EnvKnobDriftPass,
    MetricDocDriftPass,
)
from tpu_composer.analysis.passes.excepts import BareExceptPass
from tpu_composer.analysis.passes.fabric_paths import FabricMutationPathPass
from tpu_composer.analysis.passes.intent_protocol import IntentProtocolPass
from tpu_composer.analysis.passes.threads import NamedThreadPass
from tpu_composer.analysis.passes.wallclock import WallClockPass

PASSES = [
    FabricMutationPathPass(),
    IntentProtocolPass(),
    WallClockPass(),
    BareExceptPass(),
    NamedThreadPass(),
    EnvKnobDriftPass(),
    MetricDocDriftPass(),
]
