"""Doc-drift gates: env knobs and metric series vs OPERATIONS.md.

Same shape as the existing CRD-drift gate (`make validate-manifests`):
the artifact a human consumes (here docs/OPERATIONS.md, there the
generated CRD YAML) must never silently lag the source of truth.

- ``env-knob-drift``: every ``TPUC_*`` knob the CONTROL PLANE reads must
  be (a) wired in cmd/main.py — a knob only an internal module knows
  about is an undiscoverable production switch — and (b) documented in
  the OPERATIONS.md knob tables. The workload layer (workload/, ops/,
  models/, parallel/, data/ — the standalone probe/AOT harness with its
  own env contract) is out of scope by design.
- ``metric-doc-drift``: every ``tpuc_*`` series registered against the
  metrics registry must appear in OPERATIONS.md, so the runbooks' metric
  tables can be trusted to enumerate what a live operator exposes.

A wildcard mention like ``TPUC_CHAOS_STORE_*`` in OPERATIONS.md covers
every knob sharing the prefix (the chaos-store table documents the
family in one row).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional

from tpu_composer.analysis.core import (
    LintFile,
    Pass,
    Violation,
    repo_root,
    string_constants,
)

_KNOB_RE = re.compile(r"^TPUC_[A-Z0-9]+(?:_[A-Z0-9]+)*$")
_METRIC_RE = re.compile(r"^tpuc_[a-z0-9_]+$")
_WILDCARD_RE = re.compile(r"(TPUC_[A-Z0-9_]+_)\*")

#: The workload layer reads its own env contract (probe stage budgets,
#: AOT interpret overrides) and never runs inside the operator process.
_WORKLOAD_DIRS = ("workload/", "ops/", "models/", "parallel/", "data/")

_REGISTRAR_NAMES = {"counter", "gauge", "histogram"}


def _word_mentioned(name: str, doc: str) -> bool:
    """Whole-identifier match: a name that is merely a PREFIX of a longer
    documented identifier (TPUC_SLO vs TPUC_SLO_FAST_WINDOW, tpuc_slo_burn
    vs tpuc_slo_burn_rate) must NOT count as documented — substring
    containment would let the drift gate pass on an undocumented knob."""
    return (
        re.search(
            r"(?<![A-Za-z0-9_])" + re.escape(name) + r"(?![A-Za-z0-9_])", doc
        )
        is not None
    )


class _DocTargets:
    """Lazily-read wiring/doc targets, cached per pass instance so a
    full-tree run reads cmd/main.py and OPERATIONS.md once."""

    def __init__(self) -> None:
        self._main: Optional[str] = None
        self._ops: Optional[str] = None
        self._wildcards: Optional[List[str]] = None

    def main_src(self) -> str:
        if self._main is None:
            self._main = self._read(
                os.path.join("tpu_composer", "cmd", "main.py")
            )
        return self._main

    def ops_doc(self) -> str:
        if self._ops is None:
            self._ops = self._read(os.path.join("docs", "OPERATIONS.md"))
            self._wildcards = _WILDCARD_RE.findall(self._ops)
        return self._ops

    def documented(self, knob: str) -> bool:
        doc = self.ops_doc()
        if _word_mentioned(knob, doc):
            return True
        return any(knob.startswith(pref) for pref in self._wildcards or [])

    def metric_documented(self, name: str) -> bool:
        return _word_mentioned(name, self.ops_doc())

    @staticmethod
    def _read(rel: str) -> str:
        path = os.path.join(repo_root(), rel)
        try:
            with open(path, "r", encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""


class EnvKnobDriftPass(Pass):
    id = "env-knob-drift"
    invariant = (
        "every control-plane TPUC_* env knob is wired in cmd/main.py AND"
        " documented in docs/OPERATIONS.md (doc-drift gate)"
    )

    def __init__(self) -> None:
        self._targets = _DocTargets()

    def applies(self, file: LintFile) -> bool:
        rel = file.rel.replace("\\", "/")
        return not any(f"tpu_composer/{d}" in rel for d in _WORKLOAD_DIRS)

    def check(self, file: LintFile) -> Iterable[Violation]:
        if not self.applies(file):
            return []
        out: List[Violation] = []
        seen: Dict[str, int] = {}
        for const in string_constants(file.tree):
            value = const.value
            if _KNOB_RE.match(value) and value not in seen:
                seen[value] = const.lineno
        is_main = file.rel.replace("\\", "/").endswith("cmd/main.py")
        for knob, line in sorted(seen.items(), key=lambda kv: kv[1]):
            if not is_main and not _word_mentioned(
                knob, self._targets.main_src()
            ):
                out.append(
                    self.violation(
                        file,
                        line,
                        f"env knob {knob} is read here but never wired in"
                        " cmd/main.py — production switches must be"
                        " discoverable from the entrypoint",
                    )
                )
            if not self._targets.documented(knob):
                out.append(
                    self.violation(
                        file,
                        line,
                        f"env knob {knob} is not documented in"
                        " docs/OPERATIONS.md — add it to the knob table"
                        " (or cover it with a TPUC_FOO_* wildcard row)",
                    )
                )
        return out


class MetricDocDriftPass(Pass):
    id = "metric-doc-drift"
    invariant = (
        "every registered tpuc_* metric series appears in"
        " docs/OPERATIONS.md (doc-drift gate)"
    )

    def __init__(self) -> None:
        self._targets = _DocTargets()

    def check(self, file: LintFile) -> Iterable[Violation]:
        out: List[Violation] = []
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if attr.lower() not in _REGISTRAR_NAMES:
                continue
            if not node.args:
                continue
            first = node.args[0]
            if not (
                isinstance(first, ast.Constant) and isinstance(first.value, str)
            ):
                continue
            name = first.value
            if not _METRIC_RE.match(name):
                continue
            if not self._targets.metric_documented(name):
                out.append(
                    self.violation(
                        file,
                        first.lineno,
                        f"metric series {name} is registered here but"
                        " absent from docs/OPERATIONS.md — the runbook"
                        " metric tables must enumerate every live series",
                    )
                )
        return out
