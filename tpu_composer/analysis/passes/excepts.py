"""bare-except: no bare ``except:`` anywhere in the operator.

The PR 3 review lesson: ``Controller._dispatch_loop`` once caught a
mapper bug with a bare ``except:`` and silently killed the dispatch
thread — the queue looked healthy while nothing drained. A bare except
also swallows ``KeyboardInterrupt``/``SystemExit``, so a wedged worker
can't even be stopped cleanly. Catch the exception you mean
(``queue.Empty``, ``FabricError``, ...) or ``Exception`` with a loud
log; a handler that must really catch everything (none in-tree today)
says so with a suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tpu_composer.analysis.core import LintFile, Pass, Violation


class BareExceptPass(Pass):
    id = "bare-except"
    invariant = (
        "no bare `except:` — dispatch/worker loops must catch the"
        " exception they mean and log bugs loudly instead of eating them"
        " (the PR 3 dispatch-loop lesson)"
    )

    def check(self, file: LintFile) -> Iterable[Violation]:
        out: List[Violation] = []
        for node in ast.walk(file.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                out.append(
                    self.violation(
                        file,
                        node.lineno,
                        "bare `except:` — name the exception (or"
                        " `Exception` with a loud log); bare handlers eat"
                        " KeyboardInterrupt and hide worker-loop bugs",
                    )
                )
        return out
