"""fabric-mutation-path: controllers mutate the fabric only through
fence-checked paths.

The invariant (PR 8, enforced end-to-end): a fabric mutation issued by a
controller must be covered by shard fencing — ownership can flip
mid-reconcile, and the write boundary is the last place the invariant
can hold. The legal paths are:

- the dispatcher (``self.dispatcher.<verb>`` — fenced at execute/settle
  via its ``owns=`` gate),
- the fence-checked slice facade (``self._slice_fabric(req).<verb>``),
- a raw provider call inside a function that called
  ``self._fence_check(...)`` lexically BEFORE it (the designated
  ``_fabric_add``/``_fabric_remove`` wrappers).

Anything else — a bare ``self.fabric.add_resource(...)`` or
``provider.remove_resources(...)`` from controller code — is exactly the
bypass this pass exists to stop: it would mutate the fabric after a
shard lease was stolen, and the new owner's adoption pass would fight a
ghost. The cold-start adoption module is the one designated exception
(it runs pre-controller-start, before any fence exists) and carries a
file-level suppression saying so.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Tuple

from tpu_composer.analysis.core import LintFile, Pass, Violation, dotted_name

#: Every mutating verb on the FabricProvider surface (fabric/provider.py).
#: get/check/poll verbs are read-only and uncovered on purpose.
MUTATION_VERBS = {
    "add_resource",
    "remove_resource",
    "add_resources",
    "remove_resources",
    "reserve_slice",
    "release_slice",
    "resize_slice",
    "repair_slice_member",
}

#: Receivers that are themselves the fence: the dispatcher gates at
#: execute/settle, ``_slice_fabric`` raises ShardFencedError inline.
_FENCED_RECEIVER_SUFFIXES = ("dispatcher",)
_FENCED_RECEIVER_CALLS = ("_slice_fabric",)


class FabricMutationPathPass(Pass):
    id = "fabric-mutation-path"
    invariant = (
        "controllers issue fabric mutations only via the dispatcher, the"
        " _slice_fabric facade, or after a _fence_check in the same"
        " function (shard fencing at the write boundary, PR 8)"
    )

    def applies(self, file: LintFile) -> bool:
        return "controllers/" in file.rel.replace("\\", "/")

    def check(self, file: LintFile) -> Iterable[Violation]:
        if not self.applies(file):
            return []
        out: List[Violation] = []
        for func, calls, fence_lines in _scoped_mutation_calls(file.tree):
            for call, verb in calls:
                if _receiver_is_fenced(call):
                    continue
                if any(line < call.lineno for line in fence_lines):
                    continue
                out.append(
                    self.violation(
                        file,
                        call.lineno,
                        f"raw fabric mutation `{ast.unparse(call.func)}(...)`"
                        f" ({verb}) outside a fenced path — route through"
                        " the dispatcher/_slice_fabric or call"
                        " self._fence_check() first",
                    )
                )
        return out


def _scoped_mutation_calls(
    tree: ast.AST,
) -> List[Tuple[ast.AST, List[Tuple[ast.Call, str]], List[int]]]:
    """(scope, [(call, verb), ...], fence_lines) where scope is each
    call's INNERMOST enclosing function and fence_lines are the
    ``_fence_check`` calls attributed to that SAME scope. The scoping
    cuts both ways: a closure does not inherit an outer function's fence
    (the deferred body runs long after the check), and a fence inside a
    possibly-never-called closure must not cover the outer function's
    raw mutations. Module-level calls attach to the Module node."""
    mutations: List[Tuple[ast.AST, ast.Call]] = []
    fences: dict = {}

    def visit(node: ast.AST, scope: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
            if isinstance(child, ast.Call) and isinstance(
                child.func, ast.Attribute
            ):
                if child.func.attr in MUTATION_VERBS:
                    mutations.append((scope, child))
                elif child.func.attr == "_fence_check":
                    fences.setdefault(id(scope), []).append(child.lineno)
            visit(child, child_scope)

    visit(tree, tree)
    by_scope: dict = {}
    for scope, call in mutations:
        by_scope.setdefault(id(scope), (scope, [], []))[1].append(
            (call, call.func.attr)
        )
    out = []
    for scope_id, (scope, calls, _) in by_scope.items():
        out.append((scope, calls, fences.get(scope_id, [])))
    return out


def _receiver_is_fenced(call: ast.Call) -> bool:
    recv = call.func.value  # the X in X.verb(...)
    name = dotted_name(recv)
    if name and name.split(".")[-1] in _FENCED_RECEIVER_SUFFIXES:
        return True
    if isinstance(recv, ast.Call):
        inner = dotted_name(recv.func)
        if inner and inner.split(".")[-1] in _FENCED_RECEIVER_CALLS:
            return True
    return False
