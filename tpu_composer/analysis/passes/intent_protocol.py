"""intent-protocol: Attaching/Detaching transitions carry a pending_op.

The PR 5 crash-consistency protocol: the status write that makes an
``Attaching``/``Detaching`` transition durably visible must carry the
fabric-op intent (``status.pending_op``) in the SAME write — the
transition is strictly ordered before any fabric call, so a crash
anywhere past it leaves a record the cold-start adoption pass can
classify against ``fabric.get_resources()``. A transition written
WITHOUT the intent re-opens the crash window the adoption pass closed:
an attach could complete on the fabric with no durable trace, and the
restarted operator would double-attach.

AST shape checked: in controller code, an assignment of
``<obj>.status.state`` to ``RESOURCE_STATE_ATTACHING`` /
``RESOURCE_STATE_DETACHING`` (or the bare ``"Attaching"``/
``"Detaching"`` strings, including inside conditional expressions) must
be followed — in the same function, before the next ``update_status``
call — by an assignment to the same object's ``status.pending_op``.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tpu_composer.analysis.core import LintFile, Pass, Violation, dotted_name

_STATE_NAMES = {"RESOURCE_STATE_ATTACHING", "RESOURCE_STATE_DETACHING"}
_STATE_STRINGS = {"Attaching", "Detaching"}


class IntentProtocolPass(Pass):
    id = "intent-protocol"
    invariant = (
        "an Attaching/Detaching status.state transition must assign"
        " status.pending_op before the update_status that persists it"
        " (durable fabric-op intent rides the same write, PR 5)"
    )

    def applies(self, file: LintFile) -> bool:
        return "controllers/" in file.rel.replace("\\", "/")

    def check(self, file: LintFile) -> Iterable[Violation]:
        if not self.applies(file):
            return []
        out: List[Violation] = []
        for func in ast.walk(file.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            transitions = []  # (line, object prefix e.g. "res")
            pending_lines = {}  # object prefix -> [lines]
            update_lines = []
            for node in ast.walk(func):
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        name = dotted_name(target)
                        if name.endswith(".status.state") and _is_transition(
                            node.value
                        ):
                            transitions.append(
                                (node.lineno, name[: -len(".status.state")])
                            )
                        if name.endswith(".status.pending_op"):
                            pending_lines.setdefault(
                                name[: -len(".status.pending_op")], []
                            ).append(node.lineno)
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update_status"
                ):
                    update_lines.append(node.lineno)
            for line, obj in transitions:
                next_write = _next_after(update_lines, line)
                window_end = next_write if next_write is not None else 10**9
                covered = any(
                    line <= pl <= window_end
                    for pl in pending_lines.get(obj, [])
                )
                if not covered:
                    out.append(
                        self.violation(
                            file,
                            line,
                            f"`{obj}.status.state` transitions to"
                            " Attaching/Detaching without assigning"
                            f" `{obj}.status.pending_op` before the next"
                            " update_status — the durable intent must ride"
                            " the same status write",
                        )
                    )
        return out


def _is_transition(value: ast.AST) -> bool:
    """True when the assigned value can evaluate to Attaching/Detaching:
    a direct constant/name, or any such leaf inside a conditional
    expression / boolean operation."""
    for node in ast.walk(value):
        if isinstance(node, ast.Name) and node.id in _STATE_NAMES:
            return True
        if isinstance(node, ast.Constant) and node.value in _STATE_STRINGS:
            return True
    return False


def _next_after(lines: List[int], after: int) -> Optional[int]:
    following = [ln for ln in lines if ln >= after]
    return min(following) if following else None
