"""named-threads: every thread carries a ``name=``.

The PR 10 profiler attributes samples per-thread KEYED ON THE THREAD
NAME: the always-on sampler buckets ``sys._current_frames()`` stacks by
named subsystem, and the wall-vs-CPU GIL estimate
(``tpuc_gil_wait_ratio{subsystem}``) only exists for threads it can
name. An anonymous ``Thread-12`` lands in the ``other`` bucket and the
hot-spot report loses exactly the thread you were hunting. Lock-order
witness reports (analysis/lockdep.py) cite thread names too.

Checked: every ``threading.Thread(...)`` construction must pass a
``name=`` keyword. (Manager runnables are named by the manager itself
via ``_runnable_name`` — those Thread calls already carry ``name=`` and
pass this check naturally.)
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tpu_composer.analysis.core import LintFile, Pass, Violation, call_name


class NamedThreadPass(Pass):
    id = "named-threads"
    invariant = (
        "every threading.Thread is constructed with name= — profiler"
        " attribution, GIL estimates and lockdep reports key on thread"
        " names (PR 10)"
    )

    def check(self, file: LintFile) -> Iterable[Violation]:
        out: List[Violation] = []
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name not in ("threading.Thread", "Thread"):
                continue
            if name == "Thread" and not _imports_thread(file.tree):
                continue
            if any(kw.arg == "name" for kw in node.keywords):
                continue
            out.append(
                self.violation(
                    file,
                    node.lineno,
                    "threading.Thread(...) without name= — anonymous"
                    " threads attribute to the profiler's 'other' bucket"
                    " and lockdep reports can't cite them",
                )
            )
        return out


def _imports_thread(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "threading":
            if any(a.name == "Thread" for a in node.names):
                return True
    return False
