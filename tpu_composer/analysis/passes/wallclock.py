"""wall-clock: lease/expiry/steal logic never reads the wall clock.

The PR 8 observation-clock discipline: a contender steals a lease only
after the (holder, renewTime) pair sat unchanged for a full lease
duration on the contender's OWN monotonic clock — ``time.time()`` in
that logic is silently wrong (an NTP step or a VM pause can hasten a
steal, deposing a healthy leader, or block one forever). ``leases.py``
is the single module allowed to touch wall time (it renders the durable
renewTime stamps other replicas OBSERVE but never subtract).

Scope: the lease-discipline modules (runtime/shards.py,
runtime/leader.py, runtime/fleet.py — fleet staleness ages replicas out
by the same RenewObservation rule). Banned: ``time.time()``,
``datetime.now()``/``utcnow()``/``today()``. ``time.monotonic()`` /
``time.perf_counter()`` are the correct clocks and stay legal.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tpu_composer.analysis.core import LintFile, Pass, Violation, call_name

#: Modules holding steal/expiry/staleness logic. leases.py itself is the
#: deliberate exception: it OWNS the wall-clock boundary (rendering
#: renewTime stamps) and documents why.
_SCOPED = (
    "runtime/shards.py",
    "runtime/leader.py",
    "runtime/fleet.py",
)

_BANNED = {
    "time.time": "time.time()",
    "datetime.now": "datetime.now()",
    "datetime.utcnow": "datetime.utcnow()",
    "datetime.datetime.now": "datetime.now()",
    "datetime.datetime.utcnow": "datetime.utcnow()",
    "datetime.today": "datetime.today()",
}


class WallClockPass(Pass):
    id = "wall-clock"
    invariant = (
        "lease/expiry/steal logic outside leases.py uses only monotonic"
        " clocks — wall time can neither hasten nor block a failover"
        " (observation-clock discipline, PR 8)"
    )

    def applies(self, file: LintFile) -> bool:
        rel = file.rel.replace("\\", "/")
        return any(rel.endswith(s) for s in _SCOPED)

    def check(self, file: LintFile) -> Iterable[Violation]:
        if not self.applies(file):
            return []
        out: List[Violation] = []
        for node in ast.walk(file.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            banned = _BANNED.get(name)
            if banned:
                out.append(
                    self.violation(
                        file,
                        node.lineno,
                        f"wall-clock read `{banned}` in lease-discipline"
                        " code — use time.monotonic() (steal/expiry"
                        " decisions) or route durable stamps through"
                        " leases.py",
                    )
                )
        return out
