"""API object model: typed objects, metadata, scheme registry, validation.

Reference analog: /root/reference/api/v1alpha1 (CRD Go structs + generated
deepcopy + scheme registration). Here the types are plain dataclasses with
dict/JSON serde, a kind registry, and field validators mirroring the
kubebuilder validation markers.
"""

from tpu_composer.api.maintenance import (
    NodeMaintenance,
    NodeMaintenanceSpec,
    NodeMaintenanceStatus,
)
from tpu_composer.api.meta import ObjectMeta, OwnerReference, now_iso
from tpu_composer.api.scheme import Scheme, default_scheme
from tpu_composer.api.types import (
    ComposabilityRequest,
    ComposabilityRequestSpec,
    ComposabilityRequestStatus,
    ComposableResource,
    ComposableResourceSpec,
    ComposableResourceStatus,
    Node,
    NodeSpec,
    NodeStatus,
    ResourceDetails,
    ResourceStatus,
    OtherSpec,
)

__all__ = [
    "ObjectMeta",
    "OwnerReference",
    "now_iso",
    "Scheme",
    "default_scheme",
    "ComposabilityRequest",
    "ComposabilityRequestSpec",
    "ComposabilityRequestStatus",
    "ComposableResource",
    "ComposableResourceSpec",
    "ComposableResourceStatus",
    "Node",
    "NodeSpec",
    "NodeStatus",
    "NodeMaintenance",
    "NodeMaintenanceSpec",
    "NodeMaintenanceStatus",
    "ResourceDetails",
    "ResourceStatus",
    "OtherSpec",
]
