"""CRD manifest generation — keeps deploy/crds in sync with api/types.py.

Reference analog: the controller-gen-produced OpenAPI schemas under
config/crd/bases (generated from kubebuilder markers in
api/v1alpha1/*_types.go). Our schemas are built programmatically from the
same constants the Python types validate against, so the YAML can never
drift from the code: ``python -m tpu_composer.api.crdgen deploy/crds``
regenerates (the ``make manifests`` analog, Makefile:162).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List

from tpu_composer.api.types import (
    ALLOCATION_POLICIES,
    DEVICE_TYPES,
    PREEMPTION_POLICIES,
    PRIORITY_MAX,
    PRIORITY_MIN,
    REPAIR_POLICIES,
)

from tpu_composer import GROUP, VERSION  # single source of truth for the API group


def _str(desc: str = "", enum: List[str] = None, min_length: int = 0) -> Dict:
    s: Dict = {"type": "string"}
    if desc:
        s["description"] = desc
    if enum:
        s["enum"] = list(enum)
    if min_length:
        s["minLength"] = min_length
    return s


def _int(desc: str = "", minimum: int = None, maximum: int = None) -> Dict:
    s: Dict = {"type": "integer"}
    if desc:
        s["description"] = desc
    if minimum is not None:
        s["minimum"] = minimum
    if maximum is not None:
        s["maximum"] = maximum
    return s


def _bool(desc: str = "") -> Dict:
    s: Dict = {"type": "boolean"}
    if desc:
        s["description"] = desc
    return s


def _obj(props: Dict, required: List[str] = None, desc: str = "") -> Dict:
    s: Dict = {"type": "object", "properties": props}
    if required:
        s["required"] = list(required)
    if desc:
        s["description"] = desc
    return s


def _array(items: Dict, desc: str = "") -> Dict:
    s: Dict = {"type": "array", "items": items}
    if desc:
        s["description"] = desc
    return s


_OTHER_SPEC = _obj(
    {
        "milli_cpu": _int(minimum=0),
        "memory": _int(minimum=0),
        "ephemeral_storage": _int(minimum=0),
        "allowed_pod_number": _int(minimum=0),
    },
    desc="Node capacity the allocation must leave available "
    "(reference: composabilityrequest_types.go:55-64).",
)

_RESOURCE_DETAILS = _obj(
    {
        "type": _str("Device type", enum=list(DEVICE_TYPES)),
        "model": _str("Device model, e.g. tpu-v4", min_length=1),
        "size": _int("Chip count; must solve to a valid slice topology", minimum=1),
        "force_detach": _bool("Skip load checks on detach"),
        "allocation_policy": _str(enum=list(ALLOCATION_POLICIES)),
        "target_node": _str("Pin the allocation to one node (samenode only)"),
        "topology": _str("Explicit slice shape, e.g. 2x2x2 (else solved from size)"),
        "other_spec": _OTHER_SPEC,
    },
    required=["type", "model", "size"],
)

_RESOURCE_STATUS = _obj(
    {
        "state": _str(),
        "node_name": _str(),
        "device_ids": _array(_str()),
        "cdi_device_id": _str(),
        "worker_id": _int(),
        "error": _str(),
        "quarantined": _bool("Attach budget exhausted on this member"),
        "pending_verb": _str(
            "Verb of the member's in-flight fabric op (add/remove; empty"
            " when settled)"
        ),
    }
)

_FAILURE_RECORD = _obj(
    {
        "reason": _str("health-probe | device-vanished"),
        "detail": _str("Last health detail / missing device ids"),
        "source": _str("Which detector fired: health-probe | syncer"),
        "observed_at": _str("Wall-clock ISO of the Degraded transition"),
        "probe_failures": _int(
            "Consecutive failed observations that crossed the damping"
            " threshold", minimum=0,
        ),
    },
    desc="Why this member left Online for Degraded (self-healing data"
    " plane); written with the Degraded transition, cleared on recovery.",
)

_PENDING_OP = _obj(
    {
        "verb": _str(enum=["add", "remove"]),
        "nonce": _str("Unique per issued intent; survives crash/retry"),
        "node": _str(),
        "started_at": _str(),
    },
    desc="Durable fabric-mutation intent written before the attach/detach"
    " is issued and cleared when its outcome is recorded; the cold-start"
    " adoption pass reconstructs in-flight work from this after a crash.",
)

_MIGRATION_RECORD = _obj(
    {
        "member": _str("Migrating (source) member"),
        "replacement": _str("Target-side child riding the normal attach"),
        "from_node": _str(),
        "to_node": _str(),
        "trigger": _str("maintenance | evacuation | defrag"),
        "phase": _str("attaching | cutover"),
        "nonce": _str("Migration trace identity"),
        "started_at": _str(),
    },
    desc="One in-flight live migration of a slice member"
    " (make-before-break: replacement attaches, coordinates cut over,"
    " source detaches after the drain grace).",
)

_SLICE_STATUS = _obj(
    {
        "name": _str(),
        "topology": _str(),
        "num_hosts": _int(),
        "chips_per_host": _int(),
        "nodes": _array(_str(), "Hosts in worker order"),
    },
    desc="Authoritative record of the composed slice; the mutating webhook "
    "derives TPU_* coordinates from this (admission/coordinates.py).",
)

COMPOSABILITY_REQUEST_SCHEMA = _obj(
    {
        "apiVersion": _str(),
        "kind": _str(),
        "metadata": {"type": "object"},
        "spec": _obj(
            {
                "resource": _RESOURCE_DETAILS,
                "priority": _int(
                    "Scheduling priority: higher places first and may preempt"
                    " strictly-lower-priority requests (scheduler/).",
                    minimum=PRIORITY_MIN,
                    maximum=PRIORITY_MAX,
                ),
                "preemptionPolicy": _str(
                    "PreemptLowerPriority (default) or Never: Never neither"
                    " preempts nor may be preempted/defrag-migrated.",
                    enum=list(PREEMPTION_POLICIES),
                ),
                "repairPolicy": _str(
                    "Post-Ready member failure handling: Replace (default,"
                    " make-before-break replacement), DetachOnly (detach"
                    " the failed member, normal recovery re-solves), None"
                    " (no automatic action).",
                    enum=list(REPAIR_POLICIES),
                ),
                "maxConcurrentRepairs": _int(
                    "Surge budget: members of this request under active"
                    " repair at once (default 1).",
                    minimum=1,
                ),
                "repairGraceSeconds": {
                    "type": "number",
                    "minimum": 0,
                    "description": "Seconds a failed member stays attached"
                    " after its replacement is Online before the"
                    " force-detach (workload migration window).",
                },
            },
            required=["resource"],
        ),
        "status": _obj(
            {
                "state": _str(),
                "error": _str(),
                "resources": {
                    "type": "object",
                    "additionalProperties": _RESOURCE_STATUS,
                },
                "slice": _SLICE_STATUS,
                "scalar_resource": _RESOURCE_DETAILS,
                "migration": {
                    "type": "object",
                    "additionalProperties": _MIGRATION_RECORD,
                },
                "first_ready_time": _str(),
            }
        ),
    }
)

COMPOSABLE_RESOURCE_SCHEMA = _obj(
    {
        "apiVersion": _str(),
        "kind": _str(),
        "metadata": {"type": "object"},
        "spec": _obj(
            {
                "type": _str(enum=list(DEVICE_TYPES)),
                "model": _str(min_length=1),
                "target_node": _str(min_length=1),
                "force_detach": _bool(),
                "chip_count": _int(minimum=1),
                "slice_name": _str(),
                "worker_id": _int(minimum=0),
                "topology": _str(),
            },
            required=["type", "model", "target_node"],
        ),
        "status": _obj(
            {
                "state": _str(),
                "error": _str(),
                "device_ids": _array(_str()),
                "cdi_device_id": _str(),
                "chip_indices": _array(_int()),
                "attach_attempts": _int(
                    "Consecutive transient attach failures (resilience budget)"
                ),
                "quarantined": _bool(
                    "Attach budget exhausted; owner must reallocate"
                ),
                "pending_op": _PENDING_OP,
                "failure": _FAILURE_RECORD,
            }
        ),
    }
)

FLEET_TELEMETRY_SCHEMA = _obj(
    {
        "apiVersion": _str(),
        "kind": _str(),
        "metadata": {"type": "object"},
        "spec": _obj(
            {
                "identity": _str(
                    "Publishing replica identity (the shard/member lease"
                    " identity when sharded)",
                    min_length=1,
                ),
                "seq": _int(
                    "Monotonic publish counter — the aggregator's staleness"
                    " observation clock",
                    minimum=0,
                ),
                "processToken": _str(
                    "One token per OS process; histograms are merged once"
                    " per process so co-located replicas never double-count"
                ),
                "payload": {
                    "type": "object",
                    "x-kubernetes-preserve-unknown-fields": True,
                    "description": "Telemetry snapshot (histogram bucket"
                    " state, SLO burn rates, GIL ratios, profiler top-N);"
                    " shape owned by runtime/fleet.py, versioned by the"
                    " publisher — never by a CRD migration",
                },
            },
            required=["identity"],
        ),
        "status": _obj({}),
    }
)


NODE_MAINTENANCE_SCHEMA = _obj(
    {
        "apiVersion": _str(),
        "kind": _str(),
        "metadata": {"type": "object"},
        "spec": _obj(
            {
                "node_name": _str(
                    "Host to cordon and drain (live migration evacuates"
                    " every member make-before-break)",
                    min_length=1,
                ),
                "deadline_seconds": {
                    "type": "number",
                    "description": "Seconds the drain may run before"
                    " aborting; 0 uses the operator default"
                    " (--migrate-drain-deadline), negative disables the"
                    " deadline",
                },
                "reason": _str("Operator note, surfaced in events/status"),
            },
            required=["node_name"],
        ),
        "status": _obj(
            {
                "state": _str(
                    enum=["", "Cordoned", "Draining", "Drained", "Aborted"]
                ),
                "started_at": _str("Draining transition; the deadline clock"),
                "evacuated": _int(
                    "Members evacuated off the node by this drain", minimum=0
                ),
                "remaining": _int(
                    "Live members still on the node", minimum=0
                ),
                "message": _str(),
            }
        ),
    }
)


def crd(kind: str, plural: str, singular: str, short: List[str], schema: Dict) -> Dict:
    """Cluster-scoped CRD with status subresource + printer columns
    (reference: cluster-scoped markers, composabilityrequest_types.go:82-84)."""
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "scope": "Cluster",
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": singular,
                "shortNames": short,
            },
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "name": "State",
                            "type": "string",
                            "jsonPath": ".status.state",
                        },
                        {
                            "name": "Age",
                            "type": "date",
                            "jsonPath": ".metadata.creationTimestamp",
                        },
                    ],
                    "schema": {"openAPIV3Schema": schema},
                }
            ],
        },
    }


def manifests() -> Dict[str, Dict]:
    return {
        f"{GROUP}_composabilityrequests.yaml": crd(
            "ComposabilityRequest",
            "composabilityrequests",
            "composabilityrequest",
            ["creq"],
            COMPOSABILITY_REQUEST_SCHEMA,
        ),
        f"{GROUP}_composableresources.yaml": crd(
            "ComposableResource",
            "composableresources",
            "composableresource",
            ["cres"],
            COMPOSABLE_RESOURCE_SCHEMA,
        ),
        f"{GROUP}_fleettelemetries.yaml": crd(
            "FleetTelemetry",
            "fleettelemetries",
            "fleettelemetry",
            ["ftel"],
            FLEET_TELEMETRY_SCHEMA,
        ),
        f"{GROUP}_nodemaintenances.yaml": crd(
            "NodeMaintenance",
            "nodemaintenances",
            "nodemaintenance",
            ["nmaint"],
            NODE_MAINTENANCE_SCHEMA,
        ),
    }


def write_manifests(outdir: str) -> List[str]:
    import yaml

    os.makedirs(outdir, exist_ok=True)
    paths = []
    for fn, doc in manifests().items():
        path = os.path.join(outdir, fn)
        with open(path, "w") as f:
            yaml.safe_dump(doc, f, sort_keys=False)
        paths.append(path)
    return paths


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "deploy/crds"
    for p in write_manifests(out):
        print(p)
