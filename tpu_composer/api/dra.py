"""DRA-facing API objects: ResourceSlice publication + DeviceTaintRule.

Reference analog (the DRA path of the reference operator):

- attached devices become visible to the scheduler through ``ResourceSlice``
  objects the DRA kubelet plugin publishes; the operator confirms
  attachment by scanning slices for the device uuid
  (/root/reference/internal/utils/gpus.go:207-239);
- during detach the device is quarantined cluster-wide with a
  ``DeviceTaintRule`` (NoSchedule on the device uuid) before draining
  (gpus.go:894-975), deleted again once the device is gone (:959-975).

Round 1 kept taints as node-agent-local JSON — invisible to any scheduler
(VERDICT r1 missing #2). These objects make both publication and quarantine
first-class cluster state: the node agent's publisher maintains one
ResourceSlice per node (pool = node name, one entry per composed chip with
uuid/model/slice attributes), and the resource controller creates/deletes
DeviceTaintRules around the drain sequence.

Wire shapes follow resource.k8s.io/v1beta1 closely enough that KubeStore can
route them to a real apiserver group.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_composer.api.meta import ApiObject, ObjectMeta


@dataclass
class SliceDevice:
    """One schedulable device inside a ResourceSlice."""

    name: str = ""  # scheduler-visible device name, e.g. "chip-0"
    uuid: str = ""  # fabric device id (the reference scans for this, gpus.go:215-223)
    model: str = ""
    slice_name: str = ""  # owning tpu slice (ICI group)
    cdi_device_id: str = ""
    dev_path: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "basic": {
                "attributes": {
                    "uuid": {"string": self.uuid},
                    "model": {"string": self.model},
                    "slice": {"string": self.slice_name},
                    "cdiDeviceID": {"string": self.cdi_device_id},
                    "devPath": {"string": self.dev_path},
                }
            },
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SliceDevice":
        attrs = (d.get("basic") or {}).get("attributes") or {}

        def s(key: str) -> str:
            return (attrs.get(key) or {}).get("string", "")

        return cls(
            name=d.get("name", ""),
            uuid=s("uuid"),
            model=s("model"),
            slice_name=s("slice"),
            cdi_device_id=s("cdiDeviceID"),
            dev_path=s("devPath"),
        )


@dataclass
class ResourceSliceSpec:
    driver: str = "tpu.composer.dev"
    node_name: str = ""
    pool: str = ""
    devices: List[SliceDevice] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "driver": self.driver,
            "nodeName": self.node_name,
            "pool": {"name": self.pool or self.node_name,
                     "resourceSliceCount": 1},
            "devices": [d.to_dict() for d in self.devices],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceSliceSpec":
        return cls(
            driver=d.get("driver", "tpu.composer.dev"),
            node_name=d.get("nodeName", ""),
            pool=(d.get("pool") or {}).get("name", ""),
            devices=[SliceDevice.from_dict(x) for x in d.get("devices", [])],
        )

    def uuids_for_group(self, group: str) -> List[str]:
        """Chip uuids this slice publishes for one composed group
        (``SliceDevice.slice_name``). The publisher's group-scoped
        mutate/repair paths key on this — one membership definition, so
        publication and drift-repair can't disagree on what 'the group's
        devices' means."""
        return [d.uuid for d in self.devices if d.slice_name == group]

    def validate(self) -> None:
        pass


@dataclass
class ResourceSliceStatus:
    def to_dict(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceSliceStatus":
        return cls()


class ResourceSlice(ApiObject):
    KIND = "ResourceSlice"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[ResourceSliceSpec] = None,
        status: Optional[ResourceSliceStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ResourceSliceSpec()
        self.status = status or ResourceSliceStatus()

    def validate(self) -> None:
        pass


@dataclass
class DeviceTaintRuleSpec:
    """NoSchedule quarantine on one device (by uuid) or a whole node."""

    device_uuid: str = ""
    node_name: str = ""
    effect: str = "NoSchedule"
    reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "deviceSelector": {
                "device": self.device_uuid,
                "pool": self.node_name,
                "driver": "tpu.composer.dev",
            },
            "taint": {"effect": self.effect,
                      "key": "tpu.composer.dev/quarantine",
                      "value": self.reason},
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeviceTaintRuleSpec":
        sel = d.get("deviceSelector") or {}
        taint = d.get("taint") or {}
        return cls(
            device_uuid=sel.get("device", ""),
            node_name=sel.get("pool", ""),
            effect=taint.get("effect", "NoSchedule"),
            reason=taint.get("value", ""),
        )

    def validate(self) -> None:
        pass


@dataclass
class DeviceTaintRuleStatus:
    def to_dict(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeviceTaintRuleStatus":
        return cls()


class DeviceTaintRule(ApiObject):
    KIND = "DeviceTaintRule"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[DeviceTaintRuleSpec] = None,
        status: Optional[DeviceTaintRuleStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or DeviceTaintRuleSpec()
        self.status = status or DeviceTaintRuleStatus()

    def validate(self) -> None:
        pass


def taint_rule_name(device_uuid: str) -> str:
    """Deterministic rule name per device (reference: one rule per uuid,
    gpus.go:894-957)."""
    return "quarantine-" + device_uuid.replace("/", "-").replace(":", "-").lower()
