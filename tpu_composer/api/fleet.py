"""FleetTelemetry — one replica's published observability snapshot.

The sharded control plane (PR 8) made N replicas share one Store for
coordination state (shard Leases), but every observability layer stayed
per-process: N /metrics endpoints, N SLO engines each seeing 1/N of the
traffic. This kind is the carrier that closes the gap: each replica
periodically serializes its telemetry (full histogram bucket state, SLO
burn rates, per-subsystem GIL ratios, profiler top-N, owned shards) into
one ``FleetTelemetry`` object named after its identity, riding the SAME
store the shard leases already ride — so the fleet view works identically
for in-proc bench replicas and real OS processes, standalone or against a
kube-apiserver (deploy/crds carries the CRD).

The payload is deliberately schema-free on the wire (the CRD uses
``x-kubernetes-preserve-unknown-fields``): its shape is owned by
``runtime/fleet.py`` and versioned by the ``seq``-advancing publisher, not
by the API layer — a telemetry format change must never need a CRD
migration. ``seq`` is the aggregator's observation clock: a snapshot whose
sequence number sits unchanged past the staleness window marks its replica
dead, exactly the RenewObservation discipline the shard leases use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from tpu_composer.api.meta import ApiObject, ObjectMeta


@dataclass
class FleetTelemetrySpec:
    #: replica identity (the shard/member lease identity when sharded)
    identity: str = ""
    #: monotonically increasing per publish — the staleness clock
    seq: int = 0
    #: one token per OS process (pid + boot uuid): in-proc replicas share
    #: a metrics registry, so the aggregator merges histograms once per
    #: process, while per-replica fields (shards, identity) stay distinct
    process_token: str = ""
    #: the telemetry itself (runtime/fleet.py owns the shape)
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "identity": self.identity,
            "seq": self.seq,
            "processToken": self.process_token,
            "payload": self.payload,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FleetTelemetrySpec":
        return cls(
            identity=d.get("identity", "") or "",
            seq=int(d.get("seq", 0) or 0),
            process_token=d.get("processToken", "") or "",
            payload=dict(d.get("payload") or {}),
        )


@dataclass
class FleetTelemetryStatus:
    """Telemetry snapshots are spec-only (the publisher IS the source of
    truth); kept for ApiObject shape like LeaseStatus."""

    def to_dict(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FleetTelemetryStatus":
        return cls()


class FleetTelemetry(ApiObject):
    KIND = "FleetTelemetry"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[FleetTelemetrySpec] = None,
        status: Optional[FleetTelemetryStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or FleetTelemetrySpec()
        self.status = status or FleetTelemetryStatus()

    def validate(self) -> None:
        pass
