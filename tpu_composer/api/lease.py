"""coordination.k8s.io/v1 Lease — the cluster-grade leader-election object.

Reference analog: controller-runtime's manager acquires a Lease named
``c5744f42.hpsys.ibm.ie.com`` before starting any controller
(/root/reference/cmd/main.go:142-155). Round 1 only had a file lock — correct
on one host, meaningless across replicas on different nodes (VERDICT r1
missing #3). This type serializes to the real coordination.k8s.io wire form
(holderIdentity, leaseDurationSeconds, acquireTime, renewTime,
leaseTransitions) so ``KubeStore`` can CAS it against a live apiserver, while
the in-proc ``Store``'s resourceVersion conflicts give the same
compare-and-swap guarantee standalone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from tpu_composer.api.meta import ApiObject, ObjectMeta


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: str = ""
    renew_time: str = ""
    lease_transitions: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "holderIdentity": self.holder_identity,
            "leaseDurationSeconds": self.lease_duration_seconds,
            "leaseTransitions": self.lease_transitions,
        }
        if self.acquire_time:
            d["acquireTime"] = self.acquire_time
        if self.renew_time:
            d["renewTime"] = self.renew_time
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LeaseSpec":
        return cls(
            holder_identity=d.get("holderIdentity", "") or "",
            lease_duration_seconds=int(d.get("leaseDurationSeconds", 15) or 15),
            acquire_time=d.get("acquireTime", "") or "",
            renew_time=d.get("renewTime", "") or "",
            lease_transitions=int(d.get("leaseTransitions", 0) or 0),
        )


@dataclass
class LeaseStatus:
    """coordination.k8s.io Leases have no status; kept for ApiObject shape."""

    def to_dict(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LeaseStatus":
        return cls()


class Lease(ApiObject):
    KIND = "Lease"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[LeaseSpec] = None,
        status: Optional[LeaseStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or LeaseSpec()
        self.status = status or LeaseStatus()

    def validate(self) -> None:
        pass
