"""NodeMaintenance — declarative node drain for planned maintenance.

The K8s DRA composable-architecture work (PAPERS.md 2506.23628) argues for
drain/maintenance as a declarative node-side state, and Funky (PAPERS.md
2510.15755) makes checkpoint + live migration first-class operator verbs;
this kind is where the two meet in tpu-composer. Creating a NodeMaintenance
for a host:

1. **Cordon** — the maintenance controller writes the durable whole-node
   quarantine marker (the PR 1 DeviceTaintRule shape, distinct
   ``maintenance:<name>`` reason) so the scheduler routes nothing new there
   for the whole maintenance window;
2. **Drain** — every live slice member on the node is marked for
   evacuation; the owning requests' migration drivers move each one
   make-before-break (replacement attached on fresh capacity BEFORE the
   source detaches, workloads resharding on the cutover event), bounded by
   per-request surge budgets and the fleet migration breaker;
3. **Drained** — the node holds no members; hardware work can start. The
   quarantine marker stays until the NodeMaintenance is DELETED (ending the
   window uncordons the node) — mirroring kubectl cordon/uncordon.

A drain that cannot finish by ``deadline_seconds`` **aborts**: unstarted
evacuation marks are withdrawn, the quarantine marker is cleared, and the
object parks in Aborted with the reason — capacity returns instead of
wedging half-drained forever. In-flight make-before-break moves are left to
complete (aborting a half-cutover move would be strictly worse than
finishing it).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from tpu_composer.api.meta import ApiObject, ObjectMeta
from tpu_composer.api.types import ValidationError

# State machine (status.state).
MAINTENANCE_STATE_EMPTY = ""
MAINTENANCE_STATE_CORDONED = "Cordoned"
MAINTENANCE_STATE_DRAINING = "Draining"
MAINTENANCE_STATE_DRAINED = "Drained"
MAINTENANCE_STATE_ABORTED = "Aborted"

#: Quarantine-marker reason prefix for maintenance cordons — the
#: maintenance controller clears only ITS OWN marker on completion/abort,
#: never one placed by the attach-budget or node-escalation paths.
MAINTENANCE_REASON_PREFIX = "maintenance:"


@dataclass
class NodeMaintenanceSpec:
    #: Host to cordon + drain. Immutable in spirit (the webhook rejects
    #: empty; retargeting a live drain is undefined — delete and recreate).
    node_name: str = ""
    #: Seconds the drain may run before aborting; 0 falls back to the
    #: operator-wide default (--migrate-drain-deadline), < 0 disables the
    #: deadline entirely (drain until done).
    deadline_seconds: float = 0.0
    #: Free-form operator note, surfaced in events and status.
    reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"node_name": self.node_name}
        if self.deadline_seconds:
            d["deadline_seconds"] = self.deadline_seconds
        if self.reason:
            d["reason"] = self.reason
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeMaintenanceSpec":
        return cls(
            node_name=d.get("node_name", ""),
            deadline_seconds=float(d.get("deadline_seconds", 0.0) or 0.0),
            reason=d.get("reason", ""),
        )

    def validate(self) -> None:
        if not self.node_name:
            raise ValidationError("node_name must be non-empty")


@dataclass
class NodeMaintenanceStatus:
    state: str = ""
    #: Wall-clock ISO of the Draining transition — the deadline clock
    #: (crash-safe: a restarted operator resumes the same window).
    started_at: str = ""
    #: Members already evacuated off the node by this drain.
    evacuated: int = 0
    #: Live members still on the node (level-set every pass).
    remaining: int = 0
    message: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"state": self.state}
        if self.started_at:
            d["started_at"] = self.started_at
        if self.evacuated:
            d["evacuated"] = self.evacuated
        if self.remaining:
            d["remaining"] = self.remaining
        if self.message:
            d["message"] = self.message
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeMaintenanceStatus":
        return cls(
            state=d.get("state", ""),
            started_at=d.get("started_at", ""),
            evacuated=int(d.get("evacuated", 0) or 0),
            remaining=int(d.get("remaining", 0) or 0),
            message=d.get("message", ""),
        )


class NodeMaintenance(ApiObject):
    KIND = "NodeMaintenance"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[NodeMaintenanceSpec] = None,
        status: Optional[NodeMaintenanceStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or NodeMaintenanceSpec()
        self.status = status or NodeMaintenanceStatus()

    def validate(self) -> None:
        self.spec.validate()
