"""Object metadata and serde primitives.

Reference analog: k8s.io/apimachinery ObjectMeta as used by
/root/reference/api/v1alpha1/*_types.go. We implement only the fields the
reference's controllers actually rely on: name, uid, labels, annotations,
finalizers, resourceVersion (optimistic concurrency), generation,
creationTimestamp, deletionTimestamp (finalizer-gated delete), and
ownerReferences (GC of children).
"""

from __future__ import annotations

import copy
import dataclasses
import datetime
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def now_iso() -> str:
    """RFC3339 UTC timestamp, the serialization K8s uses for *Timestamp."""
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="microseconds")
        .replace("+00:00", "Z")
    )


def parse_iso(ts: str) -> datetime.datetime:
    return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))


def new_uid() -> str:
    return str(uuid.uuid4())


@dataclass
class OwnerReference:
    """Parent pointer used for cascading GC.

    Reference analog: metav1.OwnerReference; the reference instead links
    children by the label ``app.kubernetes.io/managed-by=<request>``
    (composabilityrequest_controller.go:222-235). We support both — labels for
    list-selection parity and owner refs for robust GC.
    """

    kind: str
    name: str
    uid: str = ""
    controller: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OwnerReference":
        return cls(
            kind=d["kind"],
            name=d["name"],
            uid=d.get("uid", ""),
            controller=d.get("controller", True),
        )


@dataclass
class ObjectMeta:
    name: str = ""
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)
    creation_timestamp: str = ""
    deletion_timestamp: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "uid": self.uid,
            "resourceVersion": self.resource_version,
            "generation": self.generation,
            "creationTimestamp": self.creation_timestamp,
        }
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        if self.owner_references:
            d["ownerReferences"] = [o.to_dict() for o in self.owner_references]
        if self.deletion_timestamp is not None:
            d["deletionTimestamp"] = self.deletion_timestamp
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            resource_version=int(d.get("resourceVersion", 0)),
            generation=int(d.get("generation", 0)),
            labels=dict(d.get("labels", {})),
            annotations=dict(d.get("annotations", {})),
            finalizers=list(d.get("finalizers", [])),
            owner_references=[
                OwnerReference.from_dict(o) for o in d.get("ownerReferences", [])
            ],
            creation_timestamp=d.get("creationTimestamp", ""),
            deletion_timestamp=d.get("deletionTimestamp"),
        )


class ApiObject:
    """Base for all typed API objects.

    Subclasses declare ``KIND`` and dataclass fields ``spec`` / ``status``
    (each a dataclass implementing to_dict/from_dict). Deepcopy plays the role
    of the reference's generated zz_generated.deepcopy.go.
    """

    KIND: str = ""

    metadata: ObjectMeta

    def deepcopy(self):
        return copy.deepcopy(self)

    # -- serde ------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        from tpu_composer import API_VERSION

        return {
            "apiVersion": API_VERSION,
            "kind": self.KIND,
            "metadata": self.metadata.to_dict(),
            "spec": self.spec.to_dict(),  # type: ignore[attr-defined]
            "status": self.status.to_dict(),  # type: ignore[attr-defined]
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]):
        obj = cls()  # type: ignore[call-arg]
        obj.metadata = ObjectMeta.from_dict(d.get("metadata", {}))
        spec_cls = type(obj.spec)  # type: ignore[attr-defined]
        status_cls = type(obj.status)  # type: ignore[attr-defined]
        obj.spec = spec_cls.from_dict(d.get("spec", {}))  # type: ignore[attr-defined]
        obj.status = status_cls.from_dict(d.get("status", {}))  # type: ignore[attr-defined]
        return obj

    # -- convenience used throughout the controllers ----------------------
    @property
    def name(self) -> str:
        return self.metadata.name

    def has_finalizer(self, fin: str) -> bool:
        return fin in self.metadata.finalizers

    def add_finalizer(self, fin: str) -> bool:
        if fin not in self.metadata.finalizers:
            self.metadata.finalizers.append(fin)
            return True
        return False

    def remove_finalizer(self, fin: str) -> bool:
        if fin in self.metadata.finalizers:
            self.metadata.finalizers.remove(fin)
            return True
        return False

    @property
    def being_deleted(self) -> bool:
        return self.metadata.deletion_timestamp is not None

    def owned_by(self, owner: "ApiObject") -> bool:
        return any(
            (o.uid and o.uid == owner.metadata.uid)
            or (o.kind == owner.KIND and o.name == owner.name)
            for o in self.metadata.owner_references
        )

    def set_owner(self, owner: "ApiObject") -> None:
        if not self.owned_by(owner):
            self.metadata.owner_references.append(
                OwnerReference(kind=owner.KIND, name=owner.name, uid=owner.metadata.uid)
            )

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{self.KIND} {self.metadata.name} rv={self.metadata.resource_version}>"
