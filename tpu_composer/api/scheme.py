"""Kind registry + serde entry point.

Reference analog: api/v1alpha1/groupversion_info.go:25-36 (SchemeBuilder /
AddToScheme) — maps kind strings to Go types so clients can decode. Ours maps
kind strings to Python classes for the store's persistence and any wire
encoding.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from tpu_composer.api.dra import DeviceTaintRule, ResourceSlice
from tpu_composer.api.fleet import FleetTelemetry
from tpu_composer.api.lease import Lease
from tpu_composer.api.maintenance import NodeMaintenance
from tpu_composer.api.meta import ApiObject
from tpu_composer.api.types import ComposabilityRequest, ComposableResource, Node


class SchemeError(KeyError):
    pass


class Scheme:
    def __init__(self) -> None:
        self._kinds: Dict[str, Type[ApiObject]] = {}

    def register(self, cls: Type[ApiObject]) -> None:
        if not cls.KIND:
            raise SchemeError("cannot register a class without KIND")
        self._kinds[cls.KIND] = cls

    def lookup(self, kind: str) -> Type[ApiObject]:
        try:
            return self._kinds[kind]
        except KeyError:
            raise SchemeError(f"kind {kind!r} not registered") from None

    def kinds(self):
        return sorted(self._kinds)

    def decode(self, d: Dict[str, Any]) -> ApiObject:
        kind = d.get("kind", "")
        return self.lookup(kind).from_dict(d)

    def decode_json(self, raw: str) -> ApiObject:
        return self.decode(json.loads(raw))

    @staticmethod
    def encode(obj: ApiObject) -> Dict[str, Any]:
        return obj.to_dict()

    @staticmethod
    def encode_json(obj: ApiObject) -> str:
        return json.dumps(obj.to_dict(), sort_keys=True)


def default_scheme() -> Scheme:
    s = Scheme()
    s.register(ComposabilityRequest)
    s.register(ComposableResource)
    s.register(Node)
    s.register(Lease)
    s.register(FleetTelemetry)
    s.register(NodeMaintenance)
    s.register(ResourceSlice)
    s.register(DeviceTaintRule)
    return s
