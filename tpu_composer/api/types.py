"""CRD-equivalent typed objects.

Reference analog:
- ComposabilityRequest: /root/reference/api/v1alpha1/composabilityrequest_types.go:36-106
- ComposableResource:   /root/reference/api/v1alpha1/composableresource_types.go:27-56

TPU-first deltas from the reference's data model:
- ``type: tpu`` is first-class; ``size`` means chip count and must solve to a
  valid ICI slice topology (see tpu_composer.topology.slices), not N
  independent devices.
- A ComposableResource represents one *chip group on one host* (a slice
  member), carrying ``chip_count``, ``slice_name``, ``worker_id`` and
  ``topology`` — because TPU slices are allocated as connected topologies
  (SURVEY.md §5 "slice topology" note), unlike the reference's strictly
  per-device children.
- Status carries ``device_ids`` (list of chip UUIDs) instead of the single
  ``device_id`` string at composableresource_types.go:40.
- The request status gains a ``slice`` block (topology + worker hostnames) that
  the mutating webhook uses to inject ``TPU_*`` coordinates consistently with
  the final allocation (SURVEY.md §7 hard-part #4).

State strings deliberately match the reference's controller literals
(composableresource_controller.go:107-127, composabilityrequest_controller.go:108-142)
so operational knowledge transfers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_composer.api.meta import ApiObject, ObjectMeta

# --- state machines (string literals, as the reference's controllers use) ---

# ComposabilityRequest states — composabilityrequest_controller.go:108-142
REQUEST_STATE_EMPTY = ""
REQUEST_STATE_NODE_ALLOCATING = "NodeAllocating"
REQUEST_STATE_UPDATING = "Updating"
REQUEST_STATE_RUNNING = "Running"
REQUEST_STATE_CLEANING = "Cleaning"
REQUEST_STATE_DELETING = "Deleting"

# ComposableResource states — composableresource_controller.go:107-127
RESOURCE_STATE_EMPTY = ""
RESOURCE_STATE_ATTACHING = "Attaching"
RESOURCE_STATE_ONLINE = "Online"
RESOURCE_STATE_DETACHING = "Detaching"
RESOURCE_STATE_DELETING = "Deleting"
# Self-healing additions (no reference analog — the reference's Online
# health poll only records an error string and a member with a dead chip
# sits Ready forever). Degraded: consecutive failed health probes (or the
# syncer observing the member's devices vanished from the fabric listing)
# crossed the damping threshold; the member stays attached, carries a
# structured status.failure record, and the owning request's repair driver
# decides what happens next. Repairing: the repair driver committed to
# replacing this member — a replacement child is attaching; once it is
# Online (plus the drain grace) this member is force-detached.
RESOURCE_STATE_DEGRADED = "Degraded"
RESOURCE_STATE_REPAIRING = "Repairing"
# Live migration (the evacuation analog of Repairing, but for a HEALTHY
# member being moved off its host — maintenance drain, node-escalation
# evacuation, defrag): the migration driver committed to moving this
# member; a replacement child is attaching on the target node while this
# member keeps serving. Once the replacement is Online the request's
# coordinates cut over (the slice-change event workloads reshard on) and
# this member is force-detached after the drain grace.
RESOURCE_STATE_MIGRATING = "Migrating"

# Device types — reference enum gpu|cxlmemory (composabilityrequest_types.go:41);
# tpu is our first-class addition.
DEVICE_TYPES = ("tpu", "gpu", "cxlmemory")

# Allocation policies — reference enum samenode|differentnode
# (composabilityrequest_types.go:47-49); "topology" is the TPU-native policy:
# place a connected slice across however many hosts its shape requires.
ALLOCATION_POLICIES = ("samenode", "differentnode", "topology")

# Preemption policies — modeled after PriorityClass.preemptionPolicy, but a
# single knob with victim-side meaning too: the default lets a request both
# preempt strictly-lower-priority requests and be preempted by strictly-higher
# ones; "Never" opts the request out of preemption in BOTH directions (it
# neither evicts others nor may be chosen as a victim or defrag migrant).
PREEMPT_LOWER_PRIORITY = "PreemptLowerPriority"
PREEMPT_NEVER = "Never"
PREEMPTION_POLICIES = (PREEMPT_LOWER_PRIORITY, PREEMPT_NEVER)

# Priority bounds (k8s user-priority range).
PRIORITY_MIN = -1_000_000_000
PRIORITY_MAX = 1_000_000_000

# Repair policies (spec.repairPolicy) — what the request controller does
# when a member degrades post-Ready:
#   Replace    make-before-break: place + attach a replacement member on
#              healthy capacity first, then force-detach the failed member
#              after the drain grace (default);
#   DetachOnly detach the failed member immediately and let the normal
#              lost-member recovery re-solve (break-before-make);
#   None       no automatic action — the member sits Degraded with its
#              failure record for an operator.
REPAIR_REPLACE = "Replace"
REPAIR_DETACH_ONLY = "DetachOnly"
REPAIR_NONE = "None"
REPAIR_POLICIES = (REPAIR_REPLACE, REPAIR_DETACH_ONLY, REPAIR_NONE)

FINALIZER = "tpu.composer.dev/finalizer"  # analog of com.ie.ibm.hpsys/finalizer

# Annotations (reference: cohdi.io/* at composabilityrequest_controller.go:46-47)
ANNOTATION_LAST_USED_TIME = "tpu.composer.dev/last-used-time"
ANNOTATION_DELETE_DEVICE = "tpu.composer.dev/delete-device"
# Wall-clock ISO timestamp on the syncer's orphan tracking objects: the
# first time the fabric reported a device with no local owner. Persisted so
# a controller restart cannot reset the orphan grace window (crash-loops
# would otherwise defer leak reclamation indefinitely).
ANNOTATION_ORPHAN_FIRST_SEEN = "tpu.composer.dev/orphan-first-seen"
# Repair linkage (self-healing data plane): a replacement member created by
# the repair driver names the failed member it replaces; the failed member
# names its replacement. Durable so a crash mid-repair resumes instead of
# double-placing (the surge budget and completion logic key on these).
ANNOTATION_REPLACES = "tpu.composer.dev/replaces"
ANNOTATION_REPLACED_BY = "tpu.composer.dev/replaced-by"
# Wall-clock ISO stamp set on the failed member when its replacement came
# Online: the drain grace window runs from here (crash-safe clock).
ANNOTATION_REPAIR_DRAIN_START = "tpu.composer.dev/repair-drain-start"
# Live migration (evacuation) marks. ANNOTATION_EVACUATE on a member asks
# its owner's migration driver to move it make-before-break; the value
# names the trigger ("maintenance:<name>" | "evacuation" | "defrag") so
# tpuc_migrations_total and the status.migration record attribute the move.
# Durable on the child so a crash mid-drain resumes instead of forgetting
# which members a NodeMaintenance already claimed.
ANNOTATION_EVACUATE = "tpu.composer.dev/evacuate"
# Optional placement hint from the defrag planner: the verified target the
# plan predicted. The migration driver honors it only if it still fits;
# otherwise it re-places via the scheduler like any other migration.
ANNOTATION_EVACUATE_TARGET = "tpu.composer.dev/evacuate-target"

# Migration triggers (the label values on tpuc_migrations_total{trigger}).
MIGRATE_TRIGGER_MAINTENANCE = "maintenance"
MIGRATE_TRIGGER_EVACUATION = "evacuation"
MIGRATE_TRIGGER_DEFRAG = "defrag"
LABEL_MANAGED_BY = "app.kubernetes.io/managed-by"
LABEL_READY_TO_DETACH = "tpu.composer.dev/ready-to-detach-device-id"


class ValidationError(ValueError):
    """Schema-level rejection, the analog of kubebuilder validation markers."""


# --------------------------------------------------------------------------
# Shared spec fragments
# --------------------------------------------------------------------------


@dataclass
class OtherSpec:
    """Extra node capacity the allocator must leave available.

    Reference: NodeSpec at composabilityrequest_types.go:56-64 (milli_cpu,
    memory, ephemeral_storage, allowed_pod_number) used by
    CheckNodeCapacitySufficient (utils/nodes.go:78-117).
    """

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "milli_cpu": self.milli_cpu,
            "memory": self.memory,
            "ephemeral_storage": self.ephemeral_storage,
            "allowed_pod_number": self.allowed_pod_number,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OtherSpec":
        return cls(
            milli_cpu=int(d.get("milli_cpu", 0)),
            memory=int(d.get("memory", 0)),
            ephemeral_storage=int(d.get("ephemeral_storage", 0)),
            allowed_pod_number=int(d.get("allowed_pod_number", 0)),
        )

    def validate(self) -> None:
        for f in ("milli_cpu", "memory", "ephemeral_storage", "allowed_pod_number"):
            if getattr(self, f) < 0:
                raise ValidationError(f"other_spec.{f} must be >= 0")


@dataclass
class ResourceDetails:
    """What the user asks for — reference ScalarResourceDetails
    (composabilityrequest_types.go:40-53).

    ``size`` for tpu means chip count; ``topology`` optionally pins an explicit
    slice shape (e.g. "2x2x1"); otherwise the solver picks one.
    """

    type: str = "tpu"
    model: str = ""
    size: int = 0
    force_detach: bool = False
    allocation_policy: str = "samenode"
    target_node: str = ""
    topology: str = ""
    other_spec: Optional[OtherSpec] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "type": self.type,
            "model": self.model,
            "size": self.size,
            "force_detach": self.force_detach,
            "allocation_policy": self.allocation_policy,
        }
        if self.target_node:
            d["target_node"] = self.target_node
        if self.topology:
            d["topology"] = self.topology
        if self.other_spec is not None:
            d["other_spec"] = self.other_spec.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceDetails":
        other = d.get("other_spec")
        return cls(
            type=d.get("type", "tpu"),
            model=d.get("model", ""),
            size=int(d.get("size", 0)),
            force_detach=bool(d.get("force_detach", False)),
            allocation_policy=d.get("allocation_policy", "samenode"),
            target_node=d.get("target_node", ""),
            topology=d.get("topology", ""),
            other_spec=OtherSpec.from_dict(other) if other is not None else None,
        )

    def validate(self) -> None:
        if self.type not in DEVICE_TYPES:
            raise ValidationError(
                f"resource.type must be one of {DEVICE_TYPES}, got {self.type!r}"
            )
        if not self.model:
            raise ValidationError("resource.model must be non-empty")  # MinLength=1
        if self.size < 0:
            raise ValidationError("resource.size must be >= 0")  # Minimum=0
        if self.allocation_policy not in ALLOCATION_POLICIES:
            raise ValidationError(
                f"resource.allocation_policy must be one of {ALLOCATION_POLICIES},"
                f" got {self.allocation_policy!r}"
            )
        if self.other_spec is not None:
            self.other_spec.validate()


# --------------------------------------------------------------------------
# ComposabilityRequest
# --------------------------------------------------------------------------


@dataclass
class ComposabilityRequestSpec:
    resource: ResourceDetails = field(default_factory=ResourceDetails)
    # Cluster-scheduler arbitration (scheduler/): higher priority places
    # first and may preempt strictly-lower-priority requests when capacity
    # is fragmented away. 0 is the batch default.
    priority: int = 0
    preemption_policy: str = PREEMPT_LOWER_PRIORITY
    # Self-healing: what the request controller does when a member of this
    # request degrades post-Ready (see REPAIR_POLICIES).
    repair_policy: str = REPAIR_REPLACE
    # Surge budget: at most this many members of this request may be under
    # active repair (replacement attaching / failed member draining) at
    # once — a multi-member brownout must not detach half the slice in one
    # pass.
    max_concurrent_repairs: int = 1
    # Seconds the failed member stays attached AFTER its replacement is
    # Online, so workloads can migrate off it before the force-detach.
    repair_grace_seconds: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"resource": self.resource.to_dict()}
        if self.priority:
            d["priority"] = self.priority
        if self.preemption_policy != PREEMPT_LOWER_PRIORITY:
            d["preemptionPolicy"] = self.preemption_policy
        if self.repair_policy != REPAIR_REPLACE:
            d["repairPolicy"] = self.repair_policy
        if self.max_concurrent_repairs != 1:
            d["maxConcurrentRepairs"] = self.max_concurrent_repairs
        if self.repair_grace_seconds:
            d["repairGraceSeconds"] = self.repair_grace_seconds
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComposabilityRequestSpec":
        return cls(
            resource=ResourceDetails.from_dict(d.get("resource", {})),
            priority=int(d.get("priority", 0)),
            preemption_policy=d.get("preemptionPolicy", PREEMPT_LOWER_PRIORITY),
            repair_policy=d.get("repairPolicy", REPAIR_REPLACE),
            max_concurrent_repairs=int(d.get("maxConcurrentRepairs", 1)),
            repair_grace_seconds=float(d.get("repairGraceSeconds", 0.0)),
        )

    def validate(self) -> None:
        self.resource.validate()
        if not PRIORITY_MIN <= self.priority <= PRIORITY_MAX:
            raise ValidationError(
                f"priority must be within [{PRIORITY_MIN}, {PRIORITY_MAX}],"
                f" got {self.priority}"
            )
        if self.preemption_policy not in PREEMPTION_POLICIES:
            raise ValidationError(
                f"preemptionPolicy must be one of {PREEMPTION_POLICIES},"
                f" got {self.preemption_policy!r}"
            )
        if self.repair_policy not in REPAIR_POLICIES:
            raise ValidationError(
                f"repairPolicy must be one of {REPAIR_POLICIES},"
                f" got {self.repair_policy!r}"
            )
        if self.max_concurrent_repairs < 1:
            raise ValidationError("maxConcurrentRepairs must be >= 1")
        if self.repair_grace_seconds < 0:
            raise ValidationError("repairGraceSeconds must be >= 0")


@dataclass
class PendingOp:
    """Durable record of a fabric mutation in flight for one resource.

    Written into ComposableResource.status BEFORE the attach/detach reaches
    the fabric, cleared when its outcome is recorded — so the *intent*
    survives a controller crash even when the in-memory dispatcher lanes and
    parked outcomes do not. The cold-start adoption pass
    (controllers/adoption.py) diffs these records against
    ``fabric.get_resources()`` to classify every in-flight op after a
    restart. No reference analog: the reference loses all in-flight intent
    on restart and leans entirely on its 30 s requeues + 10 min orphan
    grace to re-converge.
    """

    verb: str = ""  # "add" | "remove"
    #: Unique per issued intent; an op re-driven after a crash keeps its
    #: nonce, so a fabric mutation can be traced to exactly one intent
    #: (the kill–restart harness asserts zero double-attach on this).
    nonce: str = ""
    node: str = ""
    started_at: str = ""  # wall-clock ISO (monotonic clocks die with the process)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "verb": self.verb,
            "nonce": self.nonce,
            "node": self.node,
            "started_at": self.started_at,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PendingOp":
        return cls(
            verb=d.get("verb", ""),
            nonce=d.get("nonce", ""),
            node=d.get("node", ""),
            started_at=d.get("started_at", ""),
        )


@dataclass
class FailureRecord:
    """Structured record of why a member left Online for Degraded.

    Written by the detection paths (damped health probes in the resource
    controller, the syncer's device-vanished pass) in the same status write
    as the Degraded transition; cleared by recovery or teardown. Durable so
    a restarted operator — and the repair driver — see WHAT failed and HOW
    it was detected, not just an error string.
    """

    #: Short machine-readable cause: "health-probe" | "device-vanished".
    reason: str = ""
    detail: str = ""  # last health detail / missing device ids
    #: Which detector fired: "health-probe" | "syncer".
    source: str = ""
    observed_at: str = ""  # wall-clock ISO of the Degraded transition
    #: Consecutive failed observations that crossed the damping threshold.
    probe_failures: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"reason": self.reason}
        if self.detail:
            d["detail"] = self.detail
        if self.source:
            d["source"] = self.source
        if self.observed_at:
            d["observed_at"] = self.observed_at
        if self.probe_failures:
            d["probe_failures"] = self.probe_failures
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FailureRecord":
        return cls(
            reason=d.get("reason", ""),
            detail=d.get("detail", ""),
            source=d.get("source", ""),
            observed_at=d.get("observed_at", ""),
            probe_failures=int(d.get("probe_failures", 0)),
        )


@dataclass
class MigrationRecord:
    """One in-flight live migration of a slice member, recorded on the
    owning request's status (keyed by the migrating member's name).

    Written when the migration driver commits to the move (replacement
    child created, member marked Migrating) and removed when the source is
    detached (or the move is retired). Durable so a restarted operator —
    and any workload watching the request — sees WHERE each worker is
    moving, WHY, and how far along the make-before-break sequence it is.
    ``phase``: "attaching" (replacement still coming up; source remains the
    authoritative host) | "cutover" (replacement Online; coordinates point
    at the target and the drain grace runs before the source detach).
    """

    member: str = ""  # migrating (source) ComposableResource name
    replacement: str = ""  # the target-side child riding the normal attach
    from_node: str = ""
    to_node: str = ""
    trigger: str = ""  # maintenance | evacuation | defrag
    phase: str = ""  # attaching | cutover
    #: Migration identity: the trace id every migrate.* span joins, so the
    #: whole move renders as one connected trace across reconciles.
    nonce: str = ""
    started_at: str = ""  # wall-clock ISO (duration metric anchors here)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"member": self.member}
        if self.replacement:
            d["replacement"] = self.replacement
        if self.from_node:
            d["from_node"] = self.from_node
        if self.to_node:
            d["to_node"] = self.to_node
        if self.trigger:
            d["trigger"] = self.trigger
        if self.phase:
            d["phase"] = self.phase
        if self.nonce:
            d["nonce"] = self.nonce
        if self.started_at:
            d["started_at"] = self.started_at
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MigrationRecord":
        return cls(
            member=d.get("member", ""),
            replacement=d.get("replacement", ""),
            from_node=d.get("from_node", ""),
            to_node=d.get("to_node", ""),
            trigger=d.get("trigger", ""),
            phase=d.get("phase", ""),
            nonce=d.get("nonce", ""),
            started_at=d.get("started_at", ""),
        )


@dataclass
class ResourceStatus:
    """Per-child summary folded into the request status.

    Reference: ScalarResourceStatus (composabilityrequest_types.go:74-80), plus
    TPU additions (device_ids list, worker_id).
    """

    state: str = ""
    node_name: str = ""
    device_ids: List[str] = field(default_factory=list)
    cdi_device_id: str = ""
    worker_id: int = -1
    error: str = ""
    quarantined: bool = False
    # Verb of the child's in-flight fabric op ("add"/"remove", "" when
    # settled) — surfaced so an operator watching the request can see which
    # members still have fabric mutations outstanding (and a drain/restart
    # can be judged from the parent object alone).
    pending_verb: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"state": self.state}
        if self.node_name:
            d["node_name"] = self.node_name
        if self.device_ids:
            d["device_ids"] = list(self.device_ids)
        if self.cdi_device_id:
            d["cdi_device_id"] = self.cdi_device_id
        if self.worker_id >= 0:
            d["worker_id"] = self.worker_id
        if self.error:
            d["error"] = self.error
        if self.quarantined:
            d["quarantined"] = True
        if self.pending_verb:
            d["pending_verb"] = self.pending_verb
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ResourceStatus":
        return cls(
            state=d.get("state", ""),
            node_name=d.get("node_name", ""),
            device_ids=list(d.get("device_ids", [])),
            cdi_device_id=d.get("cdi_device_id", ""),
            worker_id=int(d.get("worker_id", -1)),
            error=d.get("error", ""),
            quarantined=bool(d.get("quarantined", False)),
            pending_verb=d.get("pending_verb", ""),
        )


@dataclass
class SliceStatus:
    """The composed-slice view used for TPU_* coordinate injection.

    No reference analog — the reference never had to keep admission output
    consistent with allocation output (SURVEY.md §7 hard-part #4); we record
    the authoritative coordinates here.
    """

    name: str = ""
    topology: str = ""
    num_hosts: int = 0
    chips_per_host: int = 0
    worker_hostnames: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {}
        if self.name:
            d["name"] = self.name
        if self.topology:
            d["topology"] = self.topology
        if self.num_hosts:
            d["num_hosts"] = self.num_hosts
        if self.chips_per_host:
            d["chips_per_host"] = self.chips_per_host
        if self.worker_hostnames:
            d["worker_hostnames"] = list(self.worker_hostnames)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SliceStatus":
        return cls(
            name=d.get("name", ""),
            topology=d.get("topology", ""),
            num_hosts=int(d.get("num_hosts", 0)),
            chips_per_host=int(d.get("chips_per_host", 0)),
            worker_hostnames=list(d.get("worker_hostnames", [])),
        )


@dataclass
class ComposabilityRequestStatus:
    state: str = ""
    error: str = ""
    resources: Dict[str, ResourceStatus] = field(default_factory=dict)
    # Spec snapshot for drift detection — reference status.scalarResource
    # (composabilityrequest_types.go:71, used at composabilityrequest_controller.go:495,:570-579)
    scalar_resource: Optional[ResourceDetails] = None
    slice: SliceStatus = field(default_factory=SliceStatus)
    # In-flight live migrations, keyed by the migrating member's name
    # (live-migration verb; see MigrationRecord).
    migration: Dict[str, MigrationRecord] = field(default_factory=dict)
    # Set once on the first transition to Running; guards the attach-to-ready
    # histogram against re-observation on recovery transitions.
    first_ready_time: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"state": self.state}
        if self.error:
            d["error"] = self.error
        if self.first_ready_time:
            d["first_ready_time"] = self.first_ready_time
        if self.resources:
            d["resources"] = {k: v.to_dict() for k, v in self.resources.items()}
        if self.scalar_resource is not None:
            d["scalarResource"] = self.scalar_resource.to_dict()
        s = self.slice.to_dict()
        if s:
            d["slice"] = s
        if self.migration:
            d["migration"] = {k: v.to_dict() for k, v in self.migration.items()}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComposabilityRequestStatus":
        sr = d.get("scalarResource")
        return cls(
            state=d.get("state", ""),
            error=d.get("error", ""),
            resources={
                k: ResourceStatus.from_dict(v) for k, v in d.get("resources", {}).items()
            },
            scalar_resource=ResourceDetails.from_dict(sr) if sr is not None else None,
            slice=SliceStatus.from_dict(d.get("slice", {})),
            migration={
                k: MigrationRecord.from_dict(v)
                for k, v in d.get("migration", {}).items()
            },
            first_ready_time=d.get("first_ready_time", ""),
        )


class ComposabilityRequest(ApiObject):
    KIND = "ComposabilityRequest"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[ComposabilityRequestSpec] = None,
        status: Optional[ComposabilityRequestStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ComposabilityRequestSpec()
        self.status = status or ComposabilityRequestStatus()

    def validate(self) -> None:
        self.spec.validate()


# --------------------------------------------------------------------------
# ComposableResource
# --------------------------------------------------------------------------


@dataclass
class ComposableResourceSpec:
    """One chip-group on one host.

    Reference: ComposableResourceSpec (composableresource_types.go:27-33) plus
    the TPU slice-membership fields.
    """

    type: str = "tpu"
    model: str = ""
    target_node: str = ""
    force_detach: bool = False
    # TPU slice membership (no reference analog; SURVEY.md §7 checklist #1)
    chip_count: int = 1
    slice_name: str = ""
    worker_id: int = 0
    topology: str = ""

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "type": self.type,
            "model": self.model,
            "target_node": self.target_node,
            "force_detach": self.force_detach,
        }
        if self.type == "tpu":
            d["chip_count"] = self.chip_count
            d["slice_name"] = self.slice_name
            d["worker_id"] = self.worker_id
            d["topology"] = self.topology
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComposableResourceSpec":
        return cls(
            type=d.get("type", "tpu"),
            model=d.get("model", ""),
            target_node=d.get("target_node", ""),
            force_detach=bool(d.get("force_detach", False)),
            chip_count=int(d.get("chip_count", 1)),
            slice_name=d.get("slice_name", ""),
            worker_id=int(d.get("worker_id", 0)),
            topology=d.get("topology", ""),
        )

    def validate(self) -> None:
        if self.type not in DEVICE_TYPES:
            raise ValidationError(f"type must be one of {DEVICE_TYPES}")
        if not self.model:
            raise ValidationError("model must be non-empty")
        if not self.target_node:
            raise ValidationError("target_node must be non-empty")
        if self.chip_count < 1:
            raise ValidationError("chip_count must be >= 1")


@dataclass
class ComposableResourceStatus:
    state: str = ""
    error: str = ""
    device_ids: List[str] = field(default_factory=list)
    cdi_device_id: str = ""
    # Host-local device-node indices (/dev/accel<i>) assigned to this group.
    # Persisted so co-located groups on one host keep disjoint nodes across
    # controller restarts (no reference analog — one GPU per CR there).
    chip_indices: List[int] = field(default_factory=list)
    # Resilience bookkeeping (docs/RESILIENCE.md): consecutive transient
    # attach failures; when the budget is exhausted the resource is marked
    # quarantined and the owning request reallocates around its node.
    # Persisted in status so the budget survives controller restarts.
    attach_attempts: int = 0
    quarantined: bool = False
    # Durable fabric-mutation intent (crash consistency): set before the
    # attach/detach is issued, cleared when its outcome lands in status.
    # The cold-start adoption pass reconstructs in-flight work from this.
    pending_op: Optional[PendingOp] = None
    # Structured cause of the Degraded transition (self-healing data plane);
    # set with the Online->Degraded write, cleared on recovery/teardown.
    failure: Optional[FailureRecord] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"state": self.state}
        if self.error:
            d["error"] = self.error
        if self.device_ids:
            d["device_ids"] = list(self.device_ids)
        if self.cdi_device_id:
            d["cdi_device_id"] = self.cdi_device_id
        if self.chip_indices:
            d["chip_indices"] = list(self.chip_indices)
        if self.attach_attempts:
            d["attach_attempts"] = self.attach_attempts
        if self.quarantined:
            d["quarantined"] = True
        if self.pending_op is not None:
            d["pending_op"] = self.pending_op.to_dict()
        if self.failure is not None:
            d["failure"] = self.failure.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ComposableResourceStatus":
        pending = d.get("pending_op")
        failure = d.get("failure")
        return cls(
            state=d.get("state", ""),
            error=d.get("error", ""),
            device_ids=list(d.get("device_ids", [])),
            cdi_device_id=d.get("cdi_device_id", ""),
            chip_indices=[int(i) for i in d.get("chip_indices", [])],
            attach_attempts=int(d.get("attach_attempts", 0)),
            quarantined=bool(d.get("quarantined", False)),
            pending_op=PendingOp.from_dict(pending) if pending else None,
            failure=FailureRecord.from_dict(failure) if failure else None,
        )


class ComposableResource(ApiObject):
    KIND = "ComposableResource"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[ComposableResourceSpec] = None,
        status: Optional[ComposableResourceStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or ComposableResourceSpec()
        self.status = status or ComposableResourceStatus()

    def validate(self) -> None:
        self.spec.validate()


# --------------------------------------------------------------------------
# Node — the worker-node view the allocator and node agent operate on.
# Reference analog: corev1.Node objects listed by utils/nodes.go:119-135 and
# capacity-checked at nodes.go:78-117. We model only what the controllers use.
# --------------------------------------------------------------------------


@dataclass
class NodeSpec:
    # Hostname or address the node agent for this node is reachable at.
    agent_endpoint: str = ""
    # Schedulable toggle (reference analog: node cordon).
    unschedulable: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "agent_endpoint": self.agent_endpoint,
            "unschedulable": self.unschedulable,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeSpec":
        return cls(
            agent_endpoint=d.get("agent_endpoint", ""),
            unschedulable=bool(d.get("unschedulable", False)),
        )


@dataclass
class NodeStatus:
    # Allocatable scalar capacity, the fields CheckNodeCapacitySufficient
    # consults (utils/nodes.go:78-117).
    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    # Max TPU chips this host can accept over the fabric (PCIe/ICI ports free).
    tpu_slots: int = 0
    ready: bool = True

    def to_dict(self) -> Dict[str, Any]:
        return {
            "milli_cpu": self.milli_cpu,
            "memory": self.memory,
            "ephemeral_storage": self.ephemeral_storage,
            "allowed_pod_number": self.allowed_pod_number,
            "tpu_slots": self.tpu_slots,
            "ready": self.ready,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NodeStatus":
        return cls(
            milli_cpu=int(d.get("milli_cpu", 0)),
            memory=int(d.get("memory", 0)),
            ephemeral_storage=int(d.get("ephemeral_storage", 0)),
            allowed_pod_number=int(d.get("allowed_pod_number", 0)),
            tpu_slots=int(d.get("tpu_slots", 0)),
            ready=bool(d.get("ready", True)),
        )


class Node(ApiObject):
    KIND = "Node"

    def __init__(
        self,
        metadata: Optional[ObjectMeta] = None,
        spec: Optional[NodeSpec] = None,
        status: Optional[NodeStatus] = None,
    ):
        self.metadata = metadata or ObjectMeta()
        self.spec = spec or NodeSpec()
        self.status = status or NodeStatus()

    def validate(self) -> None:
        pass
