"""Schema validation for the deploy artifacts — the envtest-install gate.

VERDICT r2 ask #9: the reference's suites install the generated CRDs into a
real apiserver on every run (suite_test.go:353-355), so a CRD-generation
bug cannot ship. Without apiserver binaries, this module re-implements the
two checks that install performs:

1. **Structural-schema validation of each CRD** (the apiextensions rules a
   real apiserver enforces at CRD-create time): apiVersion/kind/name
   consistency, exactly one storage version, every version carries an
   ``openAPIV3Schema`` of type object, every nested property declares a
   type (or opts out via x-kubernetes-preserve-unknown-fields), list
   schemas carry ``items``.
2. **Instance validation of the shipped examples** against those schemas —
   a mini OpenAPI checker covering the subset controller-gen emits (type,
   properties, required, items, enum, additionalProperties) — so a drift
   between api/types.py and deploy/crds fails CI, not a cluster.

Also shape-checks every document in ``dist/install.yaml`` (apiVersion,
kind, metadata.name present; workload kinds carry a pod template).

Usage: ``python -m tpu_composer.api.validate_manifests <crd-dir> <install.yaml>``
Exit 0 = everything valid; exit 1 prints each finding.
"""

from __future__ import annotations

import glob
import os
import sys
from typing import Any, Dict, Iterator, List, Tuple

import yaml

Errors = List[str]


# ---------------------------------------------------------------------------
# structural schema rules (apiserver CRD-create analog)
# ---------------------------------------------------------------------------

def _walk_schema(schema: Dict[str, Any], path: str) -> Iterator[Tuple[str, Dict[str, Any]]]:
    yield path, schema
    for name, sub in (schema.get("properties") or {}).items():
        yield from _walk_schema(sub, f"{path}.{name}")
    if isinstance(schema.get("items"), dict):
        yield from _walk_schema(schema["items"], f"{path}[]")
    if isinstance(schema.get("additionalProperties"), dict):
        yield from _walk_schema(schema["additionalProperties"], f"{path}{{}}")


def validate_crd(doc: Dict[str, Any], source: str) -> Errors:
    errs: Errors = []

    def err(msg: str) -> None:
        errs.append(f"{source}: {msg}")

    if doc.get("apiVersion") != "apiextensions.k8s.io/v1":
        err(f"apiVersion {doc.get('apiVersion')!r} != apiextensions.k8s.io/v1")
    if doc.get("kind") != "CustomResourceDefinition":
        err(f"kind {doc.get('kind')!r} != CustomResourceDefinition")
    spec = doc.get("spec") or {}
    names = spec.get("names") or {}
    for field in ("kind", "plural", "singular", "listKind"):
        if not names.get(field):
            err(f"spec.names.{field} missing")
    expected_name = f"{names.get('plural', '?')}.{spec.get('group', '?')}"
    if (doc.get("metadata") or {}).get("name") != expected_name:
        err(
            f"metadata.name {(doc.get('metadata') or {}).get('name')!r}"
            f" != <plural>.<group> ({expected_name!r})"
        )
    if spec.get("scope") not in ("Cluster", "Namespaced"):
        err(f"spec.scope {spec.get('scope')!r} invalid")

    versions = spec.get("versions") or []
    if not versions:
        err("spec.versions empty")
    storage = [v for v in versions if v.get("storage")]
    if len(storage) != 1:
        err(f"exactly one storage version required, found {len(storage)}")
    for v in versions:
        vname = v.get("name", "?")
        schema = ((v.get("schema") or {}).get("openAPIV3Schema"))
        if not isinstance(schema, dict):
            err(f"version {vname}: schema.openAPIV3Schema missing")
            continue
        if schema.get("type") != "object":
            err(f"version {vname}: root schema type must be 'object'")
        for path, node in _walk_schema(schema, vname):
            if node.get("x-kubernetes-preserve-unknown-fields"):
                continue
            if "type" not in node:
                err(f"{path}: property missing 'type' (not structural)")
                continue
            if node["type"] == "array" and "items" not in node:
                err(f"{path}: array without 'items'")
        for col in v.get("additionalPrinterColumns") or []:
            if not col.get("jsonPath", "").startswith("."):
                err(f"version {vname}: printer column jsonPath"
                    f" {col.get('jsonPath')!r} must start with '.'")
    return errs


# ---------------------------------------------------------------------------
# instance validation (the subset of OpenAPI controller-gen emits)
# ---------------------------------------------------------------------------

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
}


def validate_instance(obj: Any, schema: Dict[str, Any], path: str) -> Errors:
    errs: Errors = []
    if schema.get("x-kubernetes-preserve-unknown-fields"):
        return errs
    t = schema.get("type")
    if t == "integer":
        if not isinstance(obj, int) or isinstance(obj, bool):
            return [f"{path}: expected integer, got {type(obj).__name__}"]
    elif t == "number":
        if not isinstance(obj, (int, float)) or isinstance(obj, bool):
            return [f"{path}: expected number, got {type(obj).__name__}"]
    elif t in _TYPES and not isinstance(obj, _TYPES[t]):
        return [f"{path}: expected {t}, got {type(obj).__name__}"]
    if "enum" in schema and obj not in schema["enum"]:
        errs.append(f"{path}: {obj!r} not in enum {schema['enum']}")
    if t == "object":
        props = schema.get("properties") or {}
        for req in schema.get("required") or []:
            if req not in obj:
                errs.append(f"{path}: required field {req!r} missing")
        extra = schema.get("additionalProperties")
        for k, v in obj.items():
            if k in props:
                errs.extend(validate_instance(v, props[k], f"{path}.{k}"))
            elif isinstance(extra, dict):
                errs.extend(validate_instance(v, extra, f"{path}.{k}"))
            elif extra is False or (props and extra is None):
                # A real apiserver would silently PRUNE unknown fields;
                # flagging them here is deliberate lint strictness — a
                # pruned field in an example is a typo shipping to users.
                errs.append(f"{path}: unknown field {k!r}")
    elif t == "array":
        for i, item in enumerate(obj):
            errs.extend(
                validate_instance(item, schema.get("items") or {}, f"{path}[{i}]")
            )
    return errs


# ---------------------------------------------------------------------------
# install.yaml shape checks
# ---------------------------------------------------------------------------

_POD_TEMPLATE_KINDS = {"Deployment", "DaemonSet", "StatefulSet"}


def validate_install_doc(doc: Dict[str, Any], idx: int, source: str) -> Errors:
    errs: Errors = []
    where = f"{source}[doc {idx}]"
    for field in ("apiVersion", "kind"):
        if not doc.get(field):
            errs.append(f"{where}: {field} missing")
    if doc.get("kind") != "Namespace" and not (doc.get("metadata") or {}).get("name"):
        errs.append(f"{where}: metadata.name missing")
    if doc.get("kind") in _POD_TEMPLATE_KINDS:
        tmpl = (((doc.get("spec") or {}).get("template") or {}).get("spec") or {})
        if not tmpl.get("containers"):
            errs.append(f"{where}: {doc['kind']} without pod template containers")
    return errs


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def validate_all(crd_dir: str, install_yaml: str,
                 examples_dir: str = "examples") -> Errors:
    errs: Errors = []
    schemas_by_kind: Dict[str, Dict[str, Any]] = {}

    for path in sorted(glob.glob(os.path.join(crd_dir, "*.yaml"))):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if not doc:
                    continue
                errs.extend(validate_crd(doc, os.path.basename(path)))
                names = (doc.get("spec") or {}).get("names") or {}
                for v in (doc.get("spec") or {}).get("versions") or []:
                    schema = (v.get("schema") or {}).get("openAPIV3Schema")
                    if names.get("kind") and schema:
                        schemas_by_kind[names["kind"]] = schema

    if os.path.isdir(examples_dir):
        for path in sorted(glob.glob(os.path.join(examples_dir, "*.yaml"))):
            with open(path) as f:
                for doc in yaml.safe_load_all(f):
                    if not doc:
                        continue
                    schema = schemas_by_kind.get(doc.get("kind", ""))
                    if schema is None:
                        continue
                    errs.extend(
                        validate_instance(doc, schema, os.path.basename(path))
                    )

    if os.path.exists(install_yaml):
        with open(install_yaml) as f:
            for i, doc in enumerate(yaml.safe_load_all(f)):
                if not doc:
                    continue
                errs.extend(
                    validate_install_doc(doc, i, os.path.basename(install_yaml))
                )
                if doc.get("kind") == "CustomResourceDefinition":
                    errs.extend(validate_crd(doc, f"{install_yaml}[doc {i}]"))
    else:
        errs.append(f"{install_yaml}: not found (run `make build-installer`)")
    return errs


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    if len(args) != 2:
        print("usage: validate_manifests <crd-dir> <install.yaml>")
        return 2
    errs = validate_all(args[0], args[1])
    for e in errs:
        print(f"INVALID  {e}")
    if errs:
        return 1
    print("manifests valid")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
