"""Process entry points (reference analog: cmd/main.go)."""
