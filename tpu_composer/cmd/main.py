"""Operator process entry point.

Reference analog: cmd/main.go — flag parsing (:62-82), logger setup (:84),
manager construction with metrics/health endpoints and leader election
(:137-155), controller + webhook wiring (:167-201), healthz/readyz (:205-212),
and the blocking Start with signal handling (:214-218).

Env contract (reference analog: composableresource_adapter.go:43-70 +
SURVEY.md §5 "Config / flag system"):

  CDI_PROVIDER_TYPE   MOCK | REST_CM | REST_FM | LAYOUT | REDFISH
  FABRIC_ENDPOINT     base URL for remote providers
  FABRIC_TENANT_ID / FABRIC_CLUSTER_ID     multi-tenant path scoping
  FABRIC_AUTH_URL / FABRIC_USERNAME / FABRIC_PASSWORD /
  FABRIC_CREDENTIALS_FILE                  OAuth2 password-grant auth
  NODE_AGENT          FAKE | LOCAL (default FAKE under MOCK, LOCAL otherwise)
  ENABLE_WEBHOOKS     "false" disables in-process admission (cmd/main.go:196)
  TPUC_STATE_DIR      object-store persistence directory
  TPUC_CACHED_READS   "0" disables the watch-fed informer read cache
                      (--no-cached-reads equivalent; default on)
  TPUC_FABRIC_BATCH   "0" disables the FabricDispatcher (--no-fabric-batch
                      equivalent): attach/detach run as today's direct
                      blocking calls inside reconcile workers
  TPUC_FABRIC_EVENTS  "0" disables the fabric event plane
                      (--no-fabric-events equivalent): no FabricSession is
                      constructed and op completion is observed purely by
                      the dispatcher's poll timers, bit-identical to the
                      pre-event-plane behavior
  TPUC_FABRIC_POLL_FALLBACK_MULT
                      poll_interval stretch factor while the event session
                      is streaming (--fabric-poll-fallback-mult)
  TPUC_DRAIN_TIMEOUT  seconds a graceful shutdown drains in-flight fabric
                      ops before releasing the lease (--drain-timeout)
  TPUC_CHAOS_STORE_*  store-layer fault injection (FAILURE_RATE,
                      CONFLICT_RATE, LATENCY, WATCH_DROP_RATE, SEED) —
                      the apiserver twin of the fabric chaos knobs
  TPUC_PROFILE        "0" disables the control-plane observatory
                      (--no-profile): the always-on sampling profiler,
                      lock-contention histograms AND SLO evaluation
  TPUC_PROFILE_INTERVAL / TPUC_PROFILE_WINDOW
                      sampler tick / continuous-window size, seconds
  TPUC_PROFILE_FILE / TPUC_SLO_FILE
                      crash-hook dump destinations for the continuous-
                      profile ring and the /debug/slo snapshot
  TPUC_SLO_*          objective thresholds and burn-rate windows
                      (ATTACH_P99, COMPLETION_P50, QUEUE_P99, REPAIR_P99,
                      FAST_WINDOW, SLOW_WINDOW, BURN_THRESHOLD)
  TPUC_FLEET          "0" disables the fleet observatory (--no-fleet):
                      no telemetry publishing, no cross-replica
                      aggregation, no /debug/fleet, no replica-tagged
                      trace pids — per-process observability only
  TPUC_FLEET_PUBLISH_PERIOD / TPUC_FLEET_STALE_AFTER
                      fleet snapshot cadence / dead-replica ageing window
  TPUC_FLEET_FILE     write the /debug/fleet view here from the crash
                      hooks (--fleet-file)
  TPUC_TRACE          "0" disables causal tracing entirely (--no-trace)
  TPUC_TRACE_EVENTS   trace ring capacity in events (--trace-events)
  TPUC_TRACE_FILE     write the Chrome trace ring here at stop AND on
                      crash/drain-timeout (--trace-file)
  TPUC_FLIGHT_FILE    write the flight-recorder black box here on
                      crash/drain-timeout (--flight-file)
  TPUC_SHARDS         number of control-plane shard leases (--shards);
                      1 (default) = today's single-leader behavior,
                      K>1 = N replicas each own a hash partition of keys
  TPUC_SHARD_REPLICAS expected replica count (--shard-replicas): damps the
                      first replica's startup grab during a rolling deploy
  TPUC_REPLICA_ID     stable replica identity (--replica-id) for member
                      leases, fleet telemetry and trace process names;
                      default is a fresh hostname_uuid per boot. The
                      proc-mode supervisor (fleet/proc.py) pins one per
                      spawned replica
  TPUC_PORT_FILE      write {"pid","health_port","replica_id"} JSON here
                      after startup (--port-file) — the supervisor's
                      race-free discovery of a :0 health bind
  TPUC_LEASE_DURATION / TPUC_LEASE_RENEW_PERIOD
                      lease timing for both the single-leader and shard
                      electors (--lease-duration / --lease-renew-period)
  TPUC_POLL_SCALE     multiplier over the reconcilers' lifecycle requeue
                      cadences (attach/visibility/detach/busy/cleanup
                      re-polls); 1.0 (default) = production cadences.
                      Bench/smoke harnesses shrink it so throughput, not
                      the polling latency floor, is what gets measured

  TPUC_MIGRATE        "0" disables the live-migration verb (--no-migrate):
                      no NodeMaintenance controller, no migration driver,
                      no node-escalation evacuation, and the defrag
                      executor reverts to delete/re-solve
  TPUC_MIGRATE_MAX_CONCURRENT / TPUC_MIGRATE_BREAKER_FRACTION /
  TPUC_MIGRATE_DRAIN_DEADLINE
                      fleet migration surge cap, migration-breaker
                      threshold, and the default NodeMaintenance drain
                      deadline (--migrate-*)
  TPUC_HEALTH_FAILURE_THRESHOLD   consecutive failed health probes before
                      an Online member goes Degraded (--health-failure-threshold)
  TPUC_DECISIONS      "0" disables the scheduler decision observatory
                      (--no-decisions): no decision ledger (every
                      placement/hold-back/preemption record), no goodput
                      accounting, no capacity timeline, no
                      /debug/scheduler/* or /debug/goodput endpoints
  TPUC_DECISIONS_FILE write the decision ring here from the crash hooks
                      (--decisions-file; the soak failure artifact beside
                      the flight/profile/SLO/fleet black boxes)
  TPUC_CAPACITY_SAMPLE_PERIOD
                      seconds between capacity-timeline samples
                      (--capacity-sample-period)
  TPUC_SLO_GOODPUT_TARGET
                      goodput SLO target fraction (--slo-goodput-target;
                      0.95 = at most 5% of accounted request wall time
                      may be non-serving; <= 0 drops the objective)
  TPUC_NODE_DEGRADE_THRESHOLD     per-node Degraded transitions that
                      escalate to node quarantine (--node-degrade-threshold)
  TPUC_REPAIR_BREAKER_FRACTION / TPUC_REPAIR_BREAKER_MIN_MEMBERS
                      fleet-level repair-storm breaker (--repair-breaker-*)

Run: ``python -m tpu_composer [flags]`` or ``python -m tpu_composer.cmd.main``.

Subcommands (dispatched before operator flag parsing):

  trace-merge [--out merged.json] a.json b.json ...
      Stitch per-replica Chrome trace files (TPUC_TRACE_FILE output, one
      per replica process) into ONE connected Perfetto trace: clocks are
      aligned via each file's epoch anchor, colliding pids remapped, and
      spans sharing an intent-nonce trace id across processes joined with
      synthetic flow arrows — a kill -9 failover mid-attach renders as
      intent-by-A → adopted-by-B across two process rails.

  explain <cr> [--addr host:port] [--file decisions.json] [--json]
      Print the scheduler's decision ring for one ComposabilityRequest —
      where it landed and why, what held it back and which resource was
      binding, whom it preempted and why that set was minimal. Reads a
      running operator's /debug/scheduler/explain/<cr> (default
      127.0.0.1:8081), or a $TPUC_DECISIONS_FILE crash dump with --file.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
from typing import List, Optional

from tpu_composer.admission.validating import register_validating_webhooks
from tpu_composer.agent.fake import FakeNodeAgent
from tpu_composer.agent.nodeagent import LocalNodeAgent, NodeAgent
from tpu_composer.controllers import (
    ComposabilityRequestReconciler,
    ComposableResourceReconciler,
    UpstreamSyncer,
)
from tpu_composer.fabric.adapter import new_fabric_provider
from tpu_composer.runtime.manager import Manager
from tpu_composer.runtime.store import Store


def _env_float(name: str, default: float) -> float:
    """Env knob holding a number; a malformed value must die as a clean
    startup error, not an argparse-construction traceback."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise SystemExit(f"bad {name}={raw!r}: expected a plain number")


def _env_seconds(name: str, default: float) -> float:
    return _env_float(name, default)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise SystemExit(f"bad {name}={raw!r}: expected an integer")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu-composer",
        description="TPU-native composable-resource operator",
    )
    # Reference flags (cmd/main.go:68-81); one HTTP server carries health,
    # readiness and Prometheus metrics.
    p.add_argument(
        "--health-probe-bind-address",
        default=":8081",
        help="host:port for /healthz, /readyz and /metrics (empty to disable)",
    )
    p.add_argument(
        "--port-file",
        default=os.environ.get("TPUC_PORT_FILE", ""),
        help="after startup, write a JSON line {\"pid\", \"health_port\","
             " \"replica_id\"} here. With a :0 health bind this is how a"
             " supervisor (fleet/proc.py) discovers the real bound port"
             " race-free (env TPUC_PORT_FILE)",
    )
    # Secure metrics (reference cmd/main.go:109-127: HTTPS + authn/authz
    # filter; here TLS + bearer-token authorization from a mounted secret).
    p.add_argument(
        "--metrics-bind-address",
        default="",
        help="host:port for the dedicated secure /metrics endpoint"
             " (empty: metrics stay on the health port, plain HTTP)",
    )
    p.add_argument(
        "--metrics-cert", default="",
        help="TLS certificate for the metrics endpoint",
    )
    p.add_argument(
        "--metrics-key", default="",
        help="TLS key for the metrics endpoint",
    )
    p.add_argument(
        "--metrics-token-file", default="",
        help="file holding the bearer token scrapers must present"
             " (re-read per request; empty disables authorization)",
    )
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="enable leader election (file-lock based) before starting controllers",
    )
    p.add_argument(
        "--leader-lock-path",
        default=None,
        help="leader lock file (default under TPUC_RUN_DIR)",
    )
    # Sharded control plane (runtime/shards.py): K shard leases, N active
    # replicas each CAS-owning a balanced subset of object keys (crc32
    # consistent hash). --shards 1 is bit-identical to the single-leader
    # path: none of the shard machinery is constructed.
    p.add_argument(
        "--shards",
        type=int,
        default=_env_int("TPUC_SHARDS", 1),
        help="number of control-plane shard leases (K). 1 (default) keeps"
             " today's single-active-leader behavior unchanged; K>1 lets N"
             " replicas each own a hash partition of CR keys, with live"
             " handoff (scoped adoption) on failover/rebalance and"
             " monotonic-deadline fencing on lease loss"
             " (env TPUC_SHARDS)",
    )
    p.add_argument(
        "--shard-replicas",
        type=int,
        default=_env_int("TPUC_SHARD_REPLICAS", 0),
        help="expected operator replica count (N). Purely a startup damper:"
             " for the first lease duration a booting replica caps its grab"
             " at ceil(K/N) so a rolling deploy doesn't churn shards through"
             " replica-1; live membership (renewing replicas) governs the"
             " balance target afterwards. 0 disables"
             " (env TPUC_SHARD_REPLICAS)",
    )
    p.add_argument(
        "--replica-id",
        default=os.environ.get("TPUC_REPLICA_ID", ""),
        help="stable replica identity for shard/member leases, fleet"
             " telemetry and trace process names. Default: a fresh"
             " hostname_uuid per boot. Supervisors (fleet/proc.py) pin it"
             " so /debug/fleet and trace-merge attribute real pids without"
             " collision remapping across restarts"
             " (env TPUC_REPLICA_ID)",
    )
    p.add_argument(
        "--lease-duration",
        type=float,
        default=_env_seconds("TPUC_LEASE_DURATION", 15.0),
        help="seconds a leader/shard lease stays valid without renewal —"
             " the failover budget: a crashed replica's keys migrate to a"
             " survivor within one lease duration"
             " (env TPUC_LEASE_DURATION)",
    )
    p.add_argument(
        "--lease-renew-period",
        type=float,
        default=_env_seconds("TPUC_LEASE_RENEW_PERIOD", 5.0),
        help="seconds between lease renewals; the fencing deadline (stop"
             " acting when renewals keep failing) defaults to 2/3 of"
             " --lease-duration and is measured on the monotonic clock"
             " (env TPUC_LEASE_RENEW_PERIOD)",
    )
    p.add_argument(
        "--state-dir",
        default=os.environ.get("TPUC_STATE_DIR", ""),
        help="persist API objects under this directory (empty: in-memory only)",
    )
    # Cluster mode (reference: client-go kubeconfig/in-cluster loading,
    # cmd/main.go:161-165). Selecting a real apiserver replaces the
    # standalone store: CRs come from kubectl, nodes from kubelet.
    p.add_argument(
        "--kubeconfig",
        default="",
        help="kubeconfig path — run against a real kube-apiserver via "
             "KubeStore ($KUBECONFIG is honored unless --state-dir or "
             "--no-in-cluster selects the standalone store)",
    )
    p.add_argument(
        "--namespace",
        default=os.environ.get("TPUC_NAMESPACE", "tpu-composer-system"),
        help="namespace for the operator's namespaced objects (leader/"
             "shard Leases) in cluster mode (env TPUC_NAMESPACE)",
    )
    p.add_argument(
        "--in-cluster",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="use the pod service account to reach the apiserver. Default: "
             "auto — in-cluster when a service account token is mounted AND "
             "no --state-dir/TPUC_STATE_DIR configures standalone mode; "
             "--no-in-cluster forces the standalone store inside a pod",
    )
    p.add_argument(
        "--cached-reads",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_CACHED_READS", "1") != "0",
        help="serve controller get/list from a watch-fed informer cache;"
             " only writes pay an apiserver round trip (controller-runtime"
             " parity). --no-cached-reads or TPUC_CACHED_READS=0 reads the"
             " store directly on every call (escape hatch; semantics are"
             " identical, latency is not)",
    )
    p.add_argument(
        "--native-sched",
        action=argparse.BooleanOptionalAction,
        default=None,
        help="run placement decisions on the watch-maintained chip-index"
             " snapshot, scanned by the native kernel"
             " (native/libtpusched.so) when built, else a bit-identical"
             " pure-Python port. --no-native-sched or TPUC_NATIVE_SCHED=0"
             " restores the legacy per-decision store walks. Default:"
             " enabled (env TPUC_NATIVE_SCHED)",
    )
    p.add_argument(
        "--wire-mux",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_WIRE_MUX", "1") != "0",
        help="carry every store verb AND watch of this replica on ONE"
             " persistent framed connection (tpuc-mux/1: length-prefixed"
             " JSON frames, correlation-id pipelining, watches as"
             " server-push streams) instead of per-request keep-alive HTTP"
             " plus one dedicated connection per watch. Falls back to HTTP"
             " automatically when the apiserver has no /mux endpoint."
             " --no-wire-mux or TPUC_WIRE_MUX=0 forces the HTTP path"
             " bit-identically (cluster mode only; the standalone store"
             " has no wire)",
    )
    p.add_argument(
        "--wire-ping-period",
        type=float,
        default=_env_seconds("TPUC_WIRE_PING_PERIOD", 5.0),
        help="seconds between mux liveness ping frames; a pong outstanding"
             " past --wire-ping-misses periods declares the framed"
             " connection dead and fails every pending verb and watch at"
             " once instead of waiting out per-request timeouts. 0 (or"
             " TPUC_WIRE_PING=0, the kill switch the perf-smoke overhead"
             " gate A/Bs against) disables pings entirely (env"
             " TPUC_WIRE_PING_PERIOD)",
    )
    p.add_argument(
        "--wire-ping-misses",
        type=int,
        default=_env_int("TPUC_WIRE_PING_MISSES", 2),
        help="mux liveness deadline in ping periods: with a ping"
             " outstanding, the connection is declared dead once NO frame"
             " of any kind has arrived for (misses + 0.5) ping periods —"
             " frame-age, so a busy wire never false-positives; worst-case"
             " detection from stall onset is (misses + 0.75) periods (env"
             " TPUC_WIRE_PING_MISSES)",
    )
    p.add_argument(
        "--wire-mux-max-fails",
        type=int,
        default=_env_int("TPUC_WIRE_MUX_MAX_FAILS", 5),
        help="flap damper for the mux->HTTP fallback: degrade to plain"
             " HTTP only after this many CONSECUTIVE mux connection"
             " failures (failed dials / connections dead before a single"
             " frame); per-request failures never count and any healthy"
             " frame resets the streak (env TPUC_WIRE_MUX_MAX_FAILS)",
    )
    p.add_argument(
        "--wire-connect-timeout",
        type=float,
        default=_env_seconds("TPUC_WIRE_CONNECT_TIMEOUT", 5.0),
        help="seconds a mux (re)dial may take before failing fast — bounds"
             " how long a store call can wedge on an unreachable apiserver"
             " during a partition (env TPUC_WIRE_CONNECT_TIMEOUT)",
    )
    p.add_argument(
        "--fabric-batch",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_FABRIC_BATCH", "1") != "0",
        help="route attach/detach through the FabricDispatcher: same-node"
             " submissions coalesce into one group provider call, fabric"
             " waits are polled off-worker with one shared per-node pass,"
             " and completions re-enqueue the CR immediately."
             " --no-fabric-batch or TPUC_FABRIC_BATCH=0 restores direct"
             " blocking fabric calls inside reconcile workers",
    )
    p.add_argument(
        "--fabric-batch-window",
        type=float,
        default=_env_seconds("TPUC_FABRIC_BATCH_WINDOW", 0.02),
        help="seconds a fabric submission waits for same-node companions"
             " before dispatch (the batching/latency trade; env"
             " TPUC_FABRIC_BATCH_WINDOW)",
    )
    p.add_argument(
        "--fabric-events",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_FABRIC_EVENTS", "1") != "0",
        help="hold one persistent event session per fabric endpoint"
             " (server-push op completions, health transitions, inventory"
             " deltas; GET /v1/events for REST backends): completions"
             " settle dispatcher ops the moment the fabric finishes, and"
             " the re-poll pass stretches to a safety net. Providers"
             " without an event stream keep polling unchanged."
             " --no-fabric-events or TPUC_FABRIC_EVENTS=0 restores the"
             " poll-driven completion path bit-identically",
    )
    p.add_argument(
        "--fabric-poll-fallback-mult",
        type=float,
        default=_env_float("TPUC_FABRIC_POLL_FALLBACK_MULT", 20.0),
        help="while the event session is streaming, fabric-pending ops"
             " park at poll_interval times this factor (the safety-net"
             " cadence; anything the net catches counts"
             " tpuc_fabric_poll_fallbacks_total). Session loss snaps"
             " parked polls back to the tight poll_interval"
             " (env TPUC_FABRIC_POLL_FALLBACK_MULT)",
    )
    p.add_argument(
        "--fabric-concurrency",
        type=int,
        default=int(os.environ.get("TPUC_FABRIC_CONCURRENCY", "8")),
        help="dispatcher worker threads — concurrent fabric calls across"
             " nodes (per-node calls are always serialized FIFO; env"
             " TPUC_FABRIC_CONCURRENCY)",
    )
    p.add_argument(
        "--drain-timeout",
        type=float,
        default=_env_seconds("TPUC_DRAIN_TIMEOUT", 8.0),
        help="seconds a graceful shutdown (SIGTERM / leader handoff) waits"
             " for in-flight fabric ops to settle and their outcomes to be"
             " consumed before releasing the leader lease; <= 0 disables —"
             " in-flight intent then recovers via the cold-start adoption"
             " pass on the next start (env TPUC_DRAIN_TIMEOUT)",
    )
    # Store-layer chaos (runtime/chaosstore.py): the apiserver twin of the
    # fabric chaos knobs; all default off. See docs/OPERATIONS.md for the
    # watch-drop/cached-reads pairing caveat.
    p.add_argument(
        "--chaos-store-failure-rate", type=float,
        default=_env_float("TPUC_CHAOS_STORE_FAILURE_RATE", 0.0),
        help="probability each store call fails with a transient error"
             " (fault-injection soaks only; env TPUC_CHAOS_STORE_FAILURE_RATE)",
    )
    p.add_argument(
        "--chaos-store-conflict-rate", type=float,
        default=_env_float("TPUC_CHAOS_STORE_CONFLICT_RATE", 0.0),
        help="probability each mutating store call fails with a resource-"
             "version conflict (env TPUC_CHAOS_STORE_CONFLICT_RATE)",
    )
    p.add_argument(
        "--chaos-store-latency", type=float,
        default=_env_seconds("TPUC_CHAOS_STORE_LATENCY", 0.0),
        help="seconds of injected latency per store call"
             " (env TPUC_CHAOS_STORE_LATENCY)",
    )
    p.add_argument(
        "--chaos-store-watch-drop-rate", type=float,
        default=_env_float("TPUC_CHAOS_STORE_WATCH_DROP_RATE", 0.0),
        help="probability each watch event is dropped; pair with"
             " --no-cached-reads (the informer has no periodic resync;"
             " env TPUC_CHAOS_STORE_WATCH_DROP_RATE)",
    )
    p.add_argument(
        "--chaos-store-seed", type=int,
        default=_env_int("TPUC_CHAOS_STORE_SEED", 0),
        help="RNG seed for the store chaos injector"
             " (env TPUC_CHAOS_STORE_SEED)",
    )
    # Observability (runtime/tracing.py + runtime/lifecycle.py): causal
    # spans with cross-thread flow arrows, per-CR lifecycle timelines, and
    # the crash flight recorder. All on by default; the files are opt-in.
    p.add_argument(
        "--trace",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_TRACE", "1") != "0",
        help="record causal control-plane traces (spans + cross-thread flow"
             " arrows; /debug/traces serves them as Chrome trace-event"
             " JSON). --no-trace or TPUC_TRACE=0 turns recording into a"
             " no-op — the perf-smoke gate holds the enabled path within"
             " 5%% of this on the 32-chip wave",
    )
    p.add_argument(
        "--trace-events",
        type=int,
        default=_env_int("TPUC_TRACE_EVENTS", 10_000),
        help="trace ring capacity in events; oldest events fall off"
             " (env TPUC_TRACE_EVENTS)",
    )
    p.add_argument(
        "--trace-file",
        default=os.environ.get("TPUC_TRACE_FILE", ""),
        help="write the trace ring (Chrome trace-event JSON) here at clean"
             " stop, on drain-timeout, and from the crash hooks"
             " (env TPUC_TRACE_FILE; empty disables the file)",
    )
    p.add_argument(
        "--flight-file",
        default=os.environ.get("TPUC_FLIGHT_FILE", ""),
        help="write the flight-recorder black box (last-N state"
             " transitions, span summaries and events per CR) here on"
             " drain-timeout and from the crash hooks"
             " (env TPUC_FLIGHT_FILE; empty disables the dump)",
    )
    # Control-plane observatory (runtime/profiler.py + runtime/contention
    # + runtime/slo.py): always-on sampling profiler with per-subsystem
    # GIL-wait estimates, lock-contention histograms, and the SLO engine
    # with multi-window burn-rate alerts. One knob gates all three.
    p.add_argument(
        "--profile",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_PROFILE", "1") != "0",
        help="run the control-plane observatory: the always-on stack"
             " sampler (/debug/profile/continuous), lock wait/hold"
             " histograms on the hot locks, and SLO burn-rate evaluation"
             " (/debug/slo). --no-profile or TPUC_PROFILE=0 disables all"
             " three — the perf-smoke gate holds the enabled path within"
             " 5%% of this on the 32-chip wave. The on-demand"
             " /debug/profile burst endpoint works either way",
    )
    p.add_argument(
        "--profile-interval",
        type=float,
        default=_env_seconds("TPUC_PROFILE_INTERVAL", 0.05),
        help="always-on sampler tick, seconds (env TPUC_PROFILE_INTERVAL)",
    )
    p.add_argument(
        "--profile-window",
        type=float,
        default=_env_seconds("TPUC_PROFILE_WINDOW", 10.0),
        help="seconds per continuous-profile window; the ring keeps the"
             " most recent 30 windows (env TPUC_PROFILE_WINDOW)",
    )
    # Lockdep witness (tpu_composer/analysis/lockdep.py): ObservedLock
    # feeds per-thread held-lock stacks into a global acquisition-order
    # graph; a cycle is a potential ABBA deadlock. The test suite runs it
    # strict (raise at the offending acquire); in production it records
    # reports served on /debug/lockdep.
    p.add_argument(
        "--lockdep",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_LOCKDEP", "0") == "1",
        help="enable the lock-order witness on the observed hot locks:"
             " acquisition-order cycles (potential ABBA deadlocks) are"
             " recorded and served on /debug/lockdep (env TPUC_LOCKDEP;"
             " default off — the suite-wide strict mode lives in the test"
             " conftest)",
    )
    p.add_argument(
        "--lockdep-file",
        default=os.environ.get("TPUC_LOCKDEP_FILE", ""),
        help="dump the lockdep order graph + cycle reports here on"
             " shutdown (env TPUC_LOCKDEP_FILE; empty disables)",
    )
    p.add_argument(
        "--profile-file",
        default=os.environ.get("TPUC_PROFILE_FILE", ""),
        help="write the continuous-profile ring here from the crash hooks"
             " (the soak failure artifact; env TPUC_PROFILE_FILE)",
    )
    p.add_argument(
        "--slo-attach-p99",
        type=float,
        default=_env_seconds("TPUC_SLO_ATTACH_P99", 5.0),
        help="attach-to-ready p99 objective, seconds (<= 0 disables this"
             " objective; env TPUC_SLO_ATTACH_P99)",
    )
    p.add_argument(
        "--slo-completion-p50",
        type=float,
        default=_env_seconds("TPUC_SLO_COMPLETION_P50", 1.0),
        help="fabric completion-notification p50 objective, seconds"
             " (env TPUC_SLO_COMPLETION_P50)",
    )
    p.add_argument(
        "--slo-queue-p99",
        type=float,
        default=_env_seconds("TPUC_SLO_QUEUE_P99", 1.0),
        help="work-queue wait p99 objective, seconds"
             " (env TPUC_SLO_QUEUE_P99)",
    )
    p.add_argument(
        "--slo-repair-p99",
        type=float,
        default=_env_seconds("TPUC_SLO_REPAIR_P99", 120.0),
        help="self-healing time-to-replace p99 objective, seconds"
             " (env TPUC_SLO_REPAIR_P99)",
    )
    p.add_argument(
        "--slo-fast-window",
        type=float,
        default=_env_seconds("TPUC_SLO_FAST_WINDOW", 60.0),
        help="fast burn-rate window, seconds — reactivity and recovery"
             " (env TPUC_SLO_FAST_WINDOW)",
    )
    p.add_argument(
        "--slo-slow-window",
        type=float,
        default=_env_seconds("TPUC_SLO_SLOW_WINDOW", 600.0),
        help="slow burn-rate window, seconds — blip filtering: the alert"
             " fires only when BOTH windows burn above the threshold"
             " (env TPUC_SLO_SLOW_WINDOW)",
    )
    p.add_argument(
        "--slo-burn-threshold",
        type=float,
        default=_env_float("TPUC_SLO_BURN_THRESHOLD", 2.0),
        help="burn-rate multiple that fires the alert (1.0 = consuming"
             " exactly the error budget; env TPUC_SLO_BURN_THRESHOLD)",
    )
    p.add_argument(
        "--slo-file",
        default=os.environ.get("TPUC_SLO_FILE", ""),
        help="write the /debug/slo snapshot here from the crash hooks"
             " (env TPUC_SLO_FILE)",
    )
    # Scheduler decision observatory (scheduler/ledger.py +
    # runtime/goodput.py + runtime/capacity.py): every placement decision
    # explains itself, goodput accounting rides the lifecycle tracker,
    # and the capacity timeline samples the supply curve. One knob.
    p.add_argument(
        "--decisions",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_DECISIONS", "1") != "0",
        help="run the scheduler decision observatory: a per-CR decision"
             " ledger (inputs digest, candidate verdicts, tiebreak and"
             " binding-constraint rationale; /debug/scheduler/explain/"
             "<name> and `tpu-composer explain <cr>`), per-request goodput"
             " accounting (tpuc_goodput_ratio + the goodput SLO"
             " objective), and the capacity timeline sampler"
             " (/debug/scheduler/capacity). --no-decisions or"
             " TPUC_DECISIONS=0 constructs none of it — the perf-smoke"
             " gate holds the enabled path within 5%% of this on the"
             " 32-chip wave",
    )
    p.add_argument(
        "--decisions-file",
        default=os.environ.get("TPUC_DECISIONS_FILE", ""),
        help="write the decision ring here from the crash hooks (the soak"
             " failure artifact beside the flight/profile/SLO/fleet black"
             " boxes; env TPUC_DECISIONS_FILE)",
    )
    p.add_argument(
        "--capacity-sample-period",
        type=float,
        default=_env_seconds("TPUC_CAPACITY_SAMPLE_PERIOD", 5.0),
        help="seconds between capacity-timeline samples (largest-"
             "placeable-slice, free-chip distribution, fragmentation,"
             " goodput; env TPUC_CAPACITY_SAMPLE_PERIOD)",
    )
    p.add_argument(
        "--slo-goodput-target",
        type=float,
        default=_env_float("TPUC_SLO_GOODPUT_TARGET", 0.95),
        help="goodput SLO target: the fraction of accounted request wall"
             " time that must be Ready-serving (0.95 = a 5%% lost-time"
             " budget; burn-rate alerting like every other objective;"
             " <= 0 or --no-decisions drops the objective"
             " (env TPUC_SLO_GOODPUT_TARGET)",
    )
    # Fleet observatory (runtime/fleet.py): every replica publishes a
    # telemetry snapshot into the shared store and aggregates everyone's,
    # so /debug/fleet and tpuc_fleet_* read the same from any replica.
    p.add_argument(
        "--fleet",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_FLEET", "1") != "0",
        help="run the fleet observatory: publish this replica's telemetry"
             " snapshot (histogram bucket state, SLO burn rates, GIL"
             " ratios, owned shards) into the shared store each period,"
             " aggregate every replica's into fleet-merged SLOs and"
             " tpuc_fleet_* gauges, serve /debug/fleet, and tag trace"
             " events with the replica identity as the Chrome trace pid"
             " (what the trace-merge subcommand stitches on)."
             " --no-fleet or TPUC_FLEET=0 constructs none of it",
    )
    p.add_argument(
        "--fleet-publish-period",
        type=float,
        default=_env_seconds("TPUC_FLEET_PUBLISH_PERIOD", 2.0),
        help="seconds between fleet telemetry publishes (and aggregation"
             " ticks; env TPUC_FLEET_PUBLISH_PERIOD)",
    )
    p.add_argument(
        "--fleet-stale-after",
        type=float,
        default=_env_seconds("TPUC_FLEET_STALE_AFTER", 0.0),
        help="seconds a replica's snapshot sequence number may sit"
             " unchanged on THIS replica's monotonic clock before the"
             " replica is considered dead and leaves every fleet"
             " aggregate (0 = 5x the publish period; the leases'"
             " observation-clock discipline — wall jumps can neither"
             " hasten nor mask the ageing; env TPUC_FLEET_STALE_AFTER)",
    )
    p.add_argument(
        "--fleet-file",
        default=os.environ.get("TPUC_FLEET_FILE", ""),
        help="write the /debug/fleet view here from the crash hooks"
             " (the soak failure artifact; env TPUC_FLEET_FILE)",
    )
    # Self-healing data plane (post-Ready failure detection + repair):
    # per-request policy lives on ComposabilityRequest.spec (repairPolicy /
    # maxConcurrentRepairs / repairGraceSeconds); these are the fleet-wide
    # detection and storm-containment knobs.
    p.add_argument(
        "--health-failure-threshold",
        type=int,
        default=_env_int("TPUC_HEALTH_FAILURE_THRESHOLD", 3),
        help="consecutive failed fabric health probes before an Online"
             " member goes Degraded (flap damping: below this nothing is"
             " written; env TPUC_HEALTH_FAILURE_THRESHOLD)",
    )
    p.add_argument(
        "--node-degrade-threshold",
        type=int,
        default=_env_int("TPUC_NODE_DEGRADE_THRESHOLD", 3),
        help="Degraded transitions on one node within 10 min that escalate"
             " to a durable node quarantine (reason post-ready-failures);"
             " <= 0 disables (env TPUC_NODE_DEGRADE_THRESHOLD)",
    )
    p.add_argument(
        "--repair-breaker-fraction",
        type=float,
        default=_env_float("TPUC_REPAIR_BREAKER_FRACTION", 0.5),
        help="freeze ALL repairs while more than this fraction of attached"
             " members is Degraded/Repairing at once — a brownout is a"
             " fabric problem; mass-detaching would amplify it"
             " (env TPUC_REPAIR_BREAKER_FRACTION)",
    )
    p.add_argument(
        "--repair-breaker-min-members",
        type=int,
        default=_env_int("TPUC_REPAIR_BREAKER_MIN_MEMBERS", 4),
        help="repair breaker only arms at this many attached members —"
             " a tiny fleet's single failure is not a brownout"
             " (env TPUC_REPAIR_BREAKER_MIN_MEMBERS)",
    )
    # Live migration + node maintenance drains: the make-before-break verb
    # that evacuates capacity (NodeMaintenance drains, node-escalation
    # evacuation, defrag) without killing the job.
    p.add_argument(
        "--migrate",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_MIGRATE", "1") != "0",
        help="enable the live-migration verb: the NodeMaintenance drain"
             " controller, the request controllers' migration driver"
             " (healthy members marked for evacuation move"
             " make-before-break), node-escalation evacuation, and the"
             " defrag executor's migrate mode. --no-migrate or"
             " TPUC_MIGRATE=0 constructs none of it — no maintenance"
             " controller, no evacuations, defrag back to delete/re-solve"
             " — bit-identical to the pre-migration operator",
    )
    p.add_argument(
        "--migrate-max-concurrent",
        type=int,
        default=_env_int("TPUC_MIGRATE_MAX_CONCURRENT", 2),
        help="fleet-wide cap on members migrating at once — an N-node"
             " maintenance wave trickles instead of stampeding"
             " (per-request surge stays spec.maxConcurrentRepairs;"
             " env TPUC_MIGRATE_MAX_CONCURRENT)",
    )
    p.add_argument(
        "--migrate-breaker-fraction",
        type=float,
        default=_env_float("TPUC_MIGRATE_BREAKER_FRACTION", 0.25),
        help="freeze NEW evacuations (and park cutover detaches) while"
             " more than this fraction of attached members is"
             " Degraded/Repairing — a brownout must never trigger a mass"
             " evacuation; deliberately tighter than the repair breaker"
             " (env TPUC_MIGRATE_BREAKER_FRACTION)",
    )
    p.add_argument(
        "--migrate-drain-deadline",
        type=float,
        default=_env_seconds("TPUC_MIGRATE_DRAIN_DEADLINE", 1800.0),
        help="default NodeMaintenance drain deadline, seconds (applies"
             " when spec.deadline_seconds is 0; a drain that cannot"
             " finish aborts — marks withdrawn, node uncordoned — instead"
             " of wedging half-drained; <= 0 disables the default;"
             " env TPUC_MIGRATE_DRAIN_DEADLINE)",
    )
    p.add_argument(
        "--repair-dwell",
        type=float,
        default=_env_seconds("TPUC_REPAIR_DWELL", 0.0),
        help="seconds a member must stay Degraded before a repair may act"
             " on it — gives a lifting brownout's tail members their"
             " chance to recover in place instead of being replaced"
             " (env TPUC_REPAIR_DWELL)",
    )
    # Control-plane survival layer (runtime/overload.py, storebreaker.py,
    # watchdog.py): the governor degrades by policy under overload, the
    # store breaker rides out apiserver outages, the watchdog catches
    # wedged subsystems. Three independent escape hatches.
    p.add_argument(
        "--overload",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_OVERLOAD", "1") != "0",
        help="run the overload governor: fold queue depth, worker"
             " saturation, queue-wait p99, SLO burn and breaker states"
             " into an Ok/Warn/Shed state with hysteresis"
             " (tpuc_overload_state, /debug/overload). Warn stretches"
             " non-critical cadences (defrag, capacity sampler, fleet"
             " publish, ledger rescans); Shed additionally defers"
             " low-priority request reconciles, each deferral ledgered as"
             " a hold-back with reason=overload. --no-overload or"
             " TPUC_OVERLOAD=0 constructs none of it",
    )
    p.add_argument(
        "--overload-period",
        type=float,
        default=_env_seconds("TPUC_OVERLOAD_PERIOD", 1.0),
        help="seconds between governor evaluation ticks"
             " (env TPUC_OVERLOAD_PERIOD)",
    )
    p.add_argument(
        "--overload-depth-warn",
        type=int,
        default=_env_int("TPUC_OVERLOAD_DEPTH_WARN", 256),
        help="summed controller queue depth entering Warn"
             " (env TPUC_OVERLOAD_DEPTH_WARN)",
    )
    p.add_argument(
        "--overload-depth-shed",
        type=int,
        default=_env_int("TPUC_OVERLOAD_DEPTH_SHED", 1024),
        help="summed controller queue depth entering Shed"
             " (env TPUC_OVERLOAD_DEPTH_SHED)",
    )
    p.add_argument(
        "--overload-priority-cutoff",
        type=int,
        default=_env_int("TPUC_OVERLOAD_PRIORITY_CUTOFF", 50),
        help="requests with spec.priority below this are shed-eligible;"
             " >= keeps the tight path even while shedding"
             " (env TPUC_OVERLOAD_PRIORITY_CUTOFF)",
    )
    p.add_argument(
        "--overload-shed-quantum",
        type=float,
        default=_env_seconds("TPUC_OVERLOAD_SHED_QUANTUM", 5.0),
        help="defer quantum for shed reconciles, seconds (jittered to"
             " U(0.5, 1.0)x so releases spread;"
             " env TPUC_OVERLOAD_SHED_QUANTUM)",
    )
    p.add_argument(
        "--overload-stretch",
        type=float,
        default=_env_float("TPUC_OVERLOAD_STRETCH", 4.0),
        help="multiplier applied to non-critical cadences while in"
             " Warn/Shed (env TPUC_OVERLOAD_STRETCH)",
    )
    p.add_argument(
        "--store-breaker",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_STORE_BREAKER", "1") != "0",
        help="wrap the object store in a circuit breaker UNDER the read"
             " cache: consecutive StoreErrors trip it open (writes fail"
             " fast into per-key backoff, informer reads keep serving),"
             " a half-open probe closes it, and the close edge paces the"
             " resync herd through a token bucket"
             " (tpuc_store_breaker_open, /debug/storebreaker)."
             " --no-store-breaker or TPUC_STORE_BREAKER=0 constructs"
             " none of it",
    )
    p.add_argument(
        "--store-breaker-threshold",
        type=int,
        default=_env_int("TPUC_STORE_BREAKER_THRESHOLD", 5),
        help="consecutive store failures (StoreError; 409/404 reset the"
             " streak) that trip the breaker open"
             " (env TPUC_STORE_BREAKER_THRESHOLD)",
    )
    p.add_argument(
        "--store-breaker-reset",
        type=float,
        default=_env_seconds("TPUC_STORE_BREAKER_RESET", 5.0),
        help="seconds (±20%% jitter) before an open store breaker admits"
             " its half-open probe (env TPUC_STORE_BREAKER_RESET)",
    )
    p.add_argument(
        "--store-breaker-resync-rate",
        type=float,
        default=_env_float("TPUC_STORE_BREAKER_RESYNC_RATE", 50.0),
        help="post-heal resync pacing, wire calls per second admitted"
             " through the recovery token bucket (tpuc_resync_paced_total"
             " counts paced callers; env TPUC_STORE_BREAKER_RESYNC_RATE)",
    )
    p.add_argument(
        "--store-breaker-resync-window",
        type=float,
        default=_env_seconds("TPUC_STORE_BREAKER_RESYNC_WINDOW", 2.0),
        help="seconds after a breaker close during which the pacing"
             " bucket gates wire calls; outside it the bucket is bypassed"
             " (env TPUC_STORE_BREAKER_RESYNC_WINDOW)",
    )
    p.add_argument(
        "--watchdog",
        action=argparse.BooleanOptionalAction,
        default=os.environ.get("TPUC_WATCHDOG", "1") != "0",
        help="run the subsystem watchdog: controller workers, dispatcher"
             " lanes and manager runnables heartbeat a registry; a stalled"
             " subsystem raises a WatchdogStall Event + flight-record +"
             " on-demand profiler burst of the wedged stack"
             " (tpuc_watchdog_stalls_total), restartable runnables are"
             " respawned inside a restart budget"
             " (tpuc_watchdog_restarts_total), and chronic stalls dump"
             " the black boxes. --no-watchdog or TPUC_WATCHDOG=0"
             " constructs none of it",
    )
    p.add_argument(
        "--watchdog-stall-after",
        type=float,
        default=_env_seconds("TPUC_WATCHDOG_STALL_AFTER", 30.0),
        help="seconds without a heartbeat before a subsystem is flagged"
             " stalled (healthy workers beat multiple times per second,"
             " so the default has wide false-positive margin;"
             " env TPUC_WATCHDOG_STALL_AFTER)",
    )
    p.add_argument(
        "--watchdog-restart-budget",
        type=int,
        default=_env_int("TPUC_WATCHDOG_RESTART_BUDGET", 3),
        help="restarts allowed per restartable subsystem; past it the"
             " watchdog stops restarting and dumps the black boxes"
             " (env TPUC_WATCHDOG_RESTART_BUDGET)",
    )
    p.add_argument(
        "--workers",
        type=int,
        default=8,
        help="reconcile worker threads per controller (reconciles are "
             "IO-bound — apiserver RTTs and fabric waits — and the queue "
             "serializes per object, so workers scale attach fan-out: an "
             "8-host slice's children attach as one wave instead of four)",
    )
    p.add_argument(
        "--sync-period",
        type=float,
        default=60.0,
        help="upstream fabric anti-drift sync period, seconds (reference: 60)",
    )
    p.add_argument(
        "--sync-grace",
        type=float,
        default=600.0,
        help="grace before orphaned fabric devices are force-detached (reference: 600)",
    )
    p.add_argument(
        "--defrag-interval",
        type=float,
        default=_env_seconds("TPUC_DEFRAG_INTERVAL", 0.0),
        help="seconds between defragmentation planner passes (0 disables;"
             " env TPUC_DEFRAG_INTERVAL)",
    )
    p.add_argument(
        "--defrag-execute",
        action="store_true",
        default=os.environ.get("TPUC_DEFRAG_EXECUTE", "") == "1",
        help="execute defrag plans (migrate workers via re-solve) instead"
             " of dry-run logging them (env TPUC_DEFRAG_EXECUTE=1)",
    )
    p.add_argument(
        "--webhook-bind-address",
        default=os.environ.get("WEBHOOK_BIND_ADDRESS", ""),
        help="host:port for the AdmissionReview webhook server "
             "(reference serves :9443; empty disables the HTTP server — "
             "in-process hooks still run)",
    )
    p.add_argument(
        "--webhook-cert",
        default=os.environ.get("WEBHOOK_TLS_CERT", ""),
        help="TLS certificate for the webhook server (cert-manager mount)",
    )
    p.add_argument(
        "--webhook-key",
        default=os.environ.get("WEBHOOK_TLS_KEY", ""),
        help="TLS key for the webhook server",
    )
    p.add_argument("--log-level", default="info",
                   choices=["debug", "info", "warning", "error"])
    return p


def pick_node_agent(store: Optional[Store] = None) -> NodeAgent:
    kind = os.environ.get("NODE_AGENT", "").upper()
    if not kind:
        provider = os.environ.get("CDI_PROVIDER_TYPE", "MOCK").upper()
        kind = "FAKE" if provider == "MOCK" else "LOCAL"
    if kind == "LOCAL":
        return LocalNodeAgent()
    if kind == "REMOTE":
        # Cluster mode: route to each node's agent DaemonSet pod via
        # Node.spec.agent_endpoint, with NODE_AGENT_ENDPOINT_TEMPLATE as
        # the fallback for nodes that never registered one
        # (deploy/node-agent.yaml hostPort).
        from tpu_composer.agent.remote import RemoteNodeAgent

        if store is None:
            raise SystemExit("NODE_AGENT=REMOTE requires the store")
        return RemoteNodeAgent.from_store(
            store,
            endpoint_template=os.environ.get(
                "NODE_AGENT_ENDPOINT_TEMPLATE", "{node}:9444"
            ),
        )
    if kind == "FAKE":
        # Wired to the mock pool when that is the provider, so visibility
        # follows attachment in single-box/bench runs. With a remote
        # provider (proc-mode fleet: REST pool in another process) the
        # agent instead follows the fabric's own attachment listing — the
        # out-of-process analog of the same "chips enumerate once the
        # fabric programs the link" behavior.
        provider = new_fabric_provider()
        from tpu_composer.fabric.inmem import InMemoryPool

        if isinstance(provider, InMemoryPool):
            return FakeNodeAgent(pool=provider)
        return FakeNodeAgent(fabric=provider)
    raise SystemExit(f"unknown NODE_AGENT {kind!r} (want FAKE or LOCAL)")


def build_store(args: argparse.Namespace):
    """Standalone in-proc store, or KubeStore when a cluster is configured.

    Precedence (most explicit wins):
      1. --kubeconfig <path>          → cluster
      2. --no-in-cluster              → standalone
      3. --in-cluster                 → cluster (service account)
      4. --state-dir / TPUC_STATE_DIR → standalone (an env-derived
         $KUBECONFIG or an auto-mounted pod token must not silently
         override an explicitly configured standalone deployment)
      5. $KUBECONFIG / mounted pod service-account token → cluster
      6. otherwise                    → standalone in-memory
    """
    log = logging.getLogger("setup")
    kubeconfig = getattr(args, "kubeconfig", "")
    in_cluster = getattr(args, "in_cluster", None)
    use_cluster = bool(kubeconfig)
    if not use_cluster:
        if in_cluster is False:
            use_cluster = False
        elif in_cluster is True:
            use_cluster = True
        elif args.state_dir:
            use_cluster = False
        else:
            use_cluster = bool(os.environ.get("KUBECONFIG")) or (
                os.environ.get("KUBERNETES_SERVICE_HOST", "") != ""
                and os.path.exists(
                    "/var/run/secrets/kubernetes.io/serviceaccount/token"
                )
            )
    if use_cluster:
        from tpu_composer.runtime.kubestore import KubeConfig, KubeStore

        # KubeConfig.load owns the flag > $KUBECONFIG > in-cluster chain —
        # single source of truth for the resolution client-go encodes.
        cfg = (
            KubeConfig.in_cluster()
            if in_cluster is True and not kubeconfig
            else KubeConfig.load(kubeconfig or None)
        )
        log.info("store: kube-apiserver at %s", cfg.host)
        # KubeStore's reflector cache is the wire-path twin of the
        # standalone CachedClient — one flag governs both.
        store = KubeStore(
            config=cfg,
            cache_reads=getattr(args, "cached_reads", True),
            namespace=getattr(args, "namespace", None),
            wire_mux=getattr(args, "wire_mux", None),
            wire_ping_period=getattr(args, "wire_ping_period", None),
            wire_ping_misses=getattr(args, "wire_ping_misses", None),
            wire_mux_max_fails=getattr(args, "wire_mux_max_fails", None),
            wire_connect_timeout=getattr(args, "wire_connect_timeout", None),
        )
    else:
        log.info("store: standalone (state_dir=%s)",
                 args.state_dir or "<memory>")
        store = Store(persist_dir=args.state_dir or None)
    return _maybe_chaos_store(args, store, log)


def _maybe_chaos_store(args: argparse.Namespace, store, log):
    """Wrap the store in the chaos injector when any knob is on — same
    layer for the in-proc store and KubeStore (the faults land where wire
    faults would: between every client and the canonical state)."""
    rates = (
        getattr(args, "chaos_store_failure_rate", 0.0),
        getattr(args, "chaos_store_conflict_rate", 0.0),
        getattr(args, "chaos_store_latency", 0.0),
        getattr(args, "chaos_store_watch_drop_rate", 0.0),
    )
    if not any(r > 0 for r in rates):
        return store
    from tpu_composer.runtime.chaosstore import ChaosStore

    log.warning(
        "store chaos ON (failure=%.3f conflict=%.3f latency=%.3fs"
        " watch_drop=%.3f seed=%d) — fault-injection mode",
        rates[0], rates[1], rates[2], rates[3],
        getattr(args, "chaos_store_seed", 0),
    )
    return ChaosStore(
        store,
        failure_rate=rates[0],
        conflict_rate=rates[1],
        latency=rates[2],
        watch_drop_rate=rates[3],
        seed=getattr(args, "chaos_store_seed", 0),
    )


def _configure_tracing(args: argparse.Namespace) -> None:
    """Apply the observability knobs before any traced code runs. The file
    destinations land in the env because the crash paths (atexit, thread
    excepthook, drain-timeout) read $TPUC_TRACE_FILE / $TPUC_FLIGHT_FILE —
    they must work even when no argparse namespace is reachable."""
    from tpu_composer.runtime import tracing

    tracing.set_enabled(getattr(args, "trace", True))
    capacity = getattr(args, "trace_events", 0)
    if capacity > 0:
        # Unconditional: the ring is empty this early, so configure()'s
        # drop-contents side effect is moot.
        tracing.configure(capacity)
    if getattr(args, "trace_file", ""):
        os.environ["TPUC_TRACE_FILE"] = args.trace_file
    if getattr(args, "flight_file", ""):
        os.environ["TPUC_FLIGHT_FILE"] = args.flight_file
    # Observatory: one knob (--profile / TPUC_PROFILE) gates the sampler,
    # the lock-contention observations AND the SLO engine together.
    from tpu_composer.runtime import contention, profiler

    on = getattr(args, "profile", True)
    profiler.set_enabled(on)
    contention.set_enabled(on)
    if getattr(args, "profile_file", ""):
        os.environ["TPUC_PROFILE_FILE"] = args.profile_file
    if getattr(args, "slo_file", ""):
        os.environ["TPUC_SLO_FILE"] = args.slo_file
    if getattr(args, "fleet_file", ""):
        os.environ["TPUC_FLEET_FILE"] = args.fleet_file
    if getattr(args, "decisions_file", ""):
        os.environ["TPUC_DECISIONS_FILE"] = args.decisions_file
    # Lockdep witness: production runs non-strict (record + serve on
    # /debug/lockdep — a detector must not crash a serving operator);
    # strict raising is the TEST suite's mode, enabled by conftest.
    if getattr(args, "lockdep", False):
        from tpu_composer.analysis import lockdep

        lockdep.enable(strict=False)
    if getattr(args, "lockdep_file", ""):
        os.environ["TPUC_LOCKDEP_FILE"] = args.lockdep_file


def build_manager(args: argparse.Namespace) -> Manager:
    _configure_tracing(args)
    store = build_store(args)
    # Store circuit breaker (runtime/storebreaker.py), UNDER the read
    # cache so informer reads keep serving at zero RTT through an outage
    # while writes fail fast into per-key backoff. Deliberately NOT on
    # the `store` handle the electors/fleet use below: leases need their
    # own linearizable path, breaker-gated or not.
    storebreaker = None
    breaker_store = store
    if getattr(args, "store_breaker", True):
        from tpu_composer.runtime.storebreaker import BreakingStore

        breaker_store = BreakingStore(
            store,
            failure_threshold=getattr(args, "store_breaker_threshold", 5),
            reset_timeout=getattr(args, "store_breaker_reset", 5.0),
            resync_rate=getattr(args, "store_breaker_resync_rate", 50.0),
            resync_window=getattr(args, "store_breaker_resync_window", 2.0),
        )
        storebreaker = breaker_store
    # Informer read cache (runtime/cache.py): controllers, scheduler,
    # syncer and admission all read through `client`; only writes reach
    # `store`. KubeStore passes through unchanged (it caches internally).
    from tpu_composer.runtime.cache import maybe_cached

    client = maybe_cached(breaker_store, getattr(args, "cached_reads", True))
    from tpu_composer.fabric.adapter import TracedFabricProvider

    # Every fabric verb becomes a trace span (runtime/tracing.py); the
    # wrapper delegates everything else, so pick_node_agent's
    # InMemoryPool-identity check keeps seeing the shared mock directly.
    fabric = TracedFabricProvider(new_fabric_provider())
    agent = pick_node_agent(client)

    addr = args.health_probe_bind_address or None
    if addr and addr.startswith(":"):
        addr = "0.0.0.0" + addr
    elector = None
    ownership = None
    shard_elector = None
    num_shards = max(1, getattr(args, "shards", 1))
    if num_shards > 1:
        # Sharded control plane: K shard leases replace the single global
        # leader — every replica is active on its owned key partition.
        # Requires a SHARED store (kube-apiserver, or a shared in-proc
        # store in tests/bench); --leader-elect is subsumed.
        from tpu_composer.runtime.shards import ShardLeaseElector

        shard_elector = ShardLeaseElector(
            store,  # raw store, not the cache: leases need linearizable reads
            num_shards=num_shards,
            expected_replicas=max(0, getattr(args, "shard_replicas", 0)),
            lease_duration_s=getattr(args, "lease_duration", 15.0),
            renew_period_s=getattr(args, "lease_renew_period", 5.0),
            # Stable spawned-replica identity (proc-mode fleet): member
            # lease, fleet telemetry and trace pid all share this name.
            identity=getattr(args, "replica_id", "") or "",
        )
        ownership = shard_elector.ownership
        elector = shard_elector
        if args.leader_elect:
            logging.getLogger("setup").info(
                "--shards %d supersedes --leader-elect (every replica is"
                " active on its shard subset)", num_shards,
            )
    elif args.leader_elect:
        from tpu_composer.runtime.chaosstore import ChaosStore
        from tpu_composer.runtime.store import Store as _InProcStore

        raw_store = store._inner if isinstance(store, ChaosStore) else store
        if not isinstance(raw_store, _InProcStore):
            # Cluster mode: Lease-based election across replicas (reference
            # cmd/main.go:142-155); the file lock only fences one host.
            from tpu_composer.runtime.leases import LeaseElector

            # The raw store, not the client: leader election needs
            # linearizable Lease reads (both cache layers exclude Leases,
            # but the intent belongs in the wiring too).
            elector = LeaseElector(
                store,
                lease_duration_s=getattr(args, "lease_duration", 15.0),
                renew_period_s=getattr(args, "lease_renew_period", 5.0),
            )
    maddr = args.metrics_bind_address or None
    if maddr and maddr.startswith(":"):
        maddr = "0.0.0.0" + maddr
    if maddr and args.metrics_token_file and not args.metrics_cert:
        # The whole point of the token is that it is a secret; serving it
        # over plaintext would broadcast it to the pod network on every
        # scrape. Refuse loudly instead of degrading silently.
        raise SystemExit(
            "--metrics-token-file requires --metrics-cert/--metrics-key:"
            " bearer tokens must not transit plain HTTP"
        )
    dispatcher = None
    if getattr(args, "fabric_batch", True):
        from tpu_composer.fabric.dispatcher import FabricDispatcher

        # The dispatcher sits ABOVE the traced/breaker stack: every
        # provider call it issues (group or split) is traced and
        # breaker-guarded like a direct call would be.
        dispatcher = FabricDispatcher(
            fabric,
            batch_window=args.fabric_batch_window,
            concurrency=args.fabric_concurrency,
            # Shard fencing gate: lanes refuse ops for keys this replica
            # no longer owns (None = unsharded, no gate).
            owns=ownership.owns_key if ownership is not None else None,
            fallback_multiplier=getattr(args, "fabric_poll_fallback_mult", 20.0),
        )
    session = None
    if dispatcher is not None and getattr(args, "fabric_events", True):
        # Fabric event plane (fabric/events.py): one persistent session
        # per endpoint, server-push completions settling dispatcher ops.
        # Only meaningful WITH the dispatcher (the direct-call path blocks
        # inline and has nothing to push to); a provider without an event
        # stream answers the capability probe and the session goes
        # dormant, leaving polling primary.
        from tpu_composer.fabric.events import FabricSession

        session = FabricSession(
            fabric, name=os.environ.get("FABRIC_ENDPOINT", "") or "fabric"
        )
        dispatcher.attach_session(session)
    # Scheduler decision observatory: the goodput tracker exists before
    # the SLO engine (its objective joins the engine's list at
    # construction) and before the fleet plane (which publishes its
    # counters). TPUC_DECISIONS=0 constructs none of this.
    decisions_on = getattr(args, "decisions", True)
    goodput_tracker = None
    if decisions_on:
        from tpu_composer.runtime import lifecycle as lifecycle_mod
        from tpu_composer.runtime.goodput import GoodputTracker

        goodput_tracker = GoodputTracker()
        # Fed by the manager's lifecycle watch; Manager.stop unregisters.
        lifecycle_mod.add_transition_sink(goodput_tracker.observe)
    profiler_inst = None
    slo_engine = None
    if getattr(args, "profile", True):
        from tpu_composer.runtime.profiler import SamplingProfiler
        from tpu_composer.runtime.slo import (
            GoodputObjective,
            SloEngine,
            default_objectives,
        )

        profiler_inst = SamplingProfiler(
            interval=getattr(args, "profile_interval", 0.05),
            window_s=getattr(args, "profile_window", 10.0),
        )
        objectives = default_objectives(
            attach_p99_s=getattr(args, "slo_attach_p99", 5.0),
            completion_p50_s=getattr(args, "slo_completion_p50", 1.0),
            queue_p99_s=getattr(args, "slo_queue_p99", 1.0),
            repair_p99_s=getattr(args, "slo_repair_p99", 120.0),
        )
        goodput_target = getattr(args, "slo_goodput_target", 0.95)
        if goodput_tracker is not None and goodput_target > 0:
            objectives.append(
                GoodputObjective(goodput_tracker, target=goodput_target)
            )
        slo_engine = SloEngine(
            objectives=objectives,
            fast_window=getattr(args, "slo_fast_window", 60.0),
            slow_window=getattr(args, "slo_slow_window", 600.0),
            burn_threshold=getattr(args, "slo_burn_threshold", 2.0),
        )
    fleet_plane = None
    replica_id = None
    if not getattr(args, "fleet", True):
        if shard_elector is not None:
            # --no-fleet means NO replica-tagged trace pids anywhere —
            # the elector's renew thread included (its default tagging
            # serves direct harness wiring, not the escape hatch).
            shard_elector.tag_traces = False
    else:
        # Fleet observatory: identity follows the shard/member lease
        # identity when sharded (the fleet view and the lease table must
        # name replicas identically), a fresh per-boot identity otherwise.
        from tpu_composer.runtime import tracing as tracing_mod
        from tpu_composer.runtime.fleet import FleetPlane
        from tpu_composer.runtime.leases import default_identity

        replica_id = (
            shard_elector.identity if shard_elector is not None
            else getattr(args, "replica_id", "") or default_identity()
        )
        # Every trace event this process records carries the replica
        # identity as its Chrome trace pid — what `tpu-composer
        # trace-merge` stitches cross-process failovers on.
        tracing_mod.set_replica(replica_id)
        fleet_plane = FleetPlane(
            store,  # raw store, not the cache: snapshots ride beside leases
            identity=replica_id,
            num_shards=num_shards,
            ownership=ownership,
            publish_period=getattr(args, "fleet_publish_period", 2.0),
            stale_after_s=getattr(args, "fleet_stale_after", 0.0),
            attach_p99_s=getattr(args, "slo_attach_p99", 5.0),
            queue_p99_s=getattr(args, "slo_queue_p99", 1.0),
            fast_window=getattr(args, "slo_fast_window", 60.0),
            slow_window=getattr(args, "slo_slow_window", 600.0),
            burn_threshold=getattr(args, "slo_burn_threshold", 2.0),
            slo_engine=slo_engine,
            profiler=profiler_inst,
            goodput=goodput_tracker,
        )
    # Subsystem watchdog (runtime/watchdog.py): controller workers,
    # dispatcher lanes and the governor heartbeat it; Manager.start hands
    # it the runnable-respawn hook.
    watchdog = None
    if getattr(args, "watchdog", True):
        from tpu_composer.runtime.watchdog import Watchdog

        watchdog = Watchdog(
            stall_after=getattr(args, "watchdog_stall_after", 30.0),
            restart_budget=getattr(args, "watchdog_restart_budget", 3),
        )
        if dispatcher is not None:
            dispatcher.watchdog = watchdog
    mgr = Manager(
        store=client,
        leader_elect=args.leader_elect,
        leader_lock_path=args.leader_lock_path,
        health_addr=addr,
        leader_elector=elector,
        metrics_addr=maddr,
        metrics_certfile=args.metrics_cert or None,
        metrics_keyfile=args.metrics_key or None,
        metrics_token_file=args.metrics_token_file or None,
        dispatcher=dispatcher,
        drain_timeout=getattr(args, "drain_timeout", 8.0),
        profiler=profiler_inst,
        slo_engine=slo_engine,
        replica_id=replica_id,
        fleet=fleet_plane,
        goodput=goodput_tracker,
        watchdog=watchdog,
        storebreaker=storebreaker,
    )
    if watchdog is not None:
        watchdog.recorder = mgr.recorder
        mgr.add_runnable(watchdog.run)
    if slo_engine is not None:
        # The engine's breach/recovery Events flow through the manager's
        # recorder (constructed just above).
        slo_engine.recorder = mgr.recorder
    if fleet_plane is not None:
        # Fleet SLO breach Events ride the same recorder as local ones.
        fleet_plane.slo.recorder = mgr.recorder
        mgr.add_runnable(fleet_plane.run)
    if dispatcher is not None:
        mgr.add_runnable(dispatcher.run)
    if session is not None:
        mgr.add_runnable(session.run)
    # Cold-start adoption (controllers/adoption.py): post-leader-acquire,
    # pre-controller-start, every durable pending_op intent is classified
    # against the live fabric — completed attaches are adopted into
    # status, never-issued ops cleared for re-submission, fabric-async
    # ops handed to the dispatcher's re-poll pass.
    from tpu_composer.controllers.adoption import adopt_pending_ops

    if shard_elector is not None:
        # Shard acquisition IS the adoption trigger: every shard this
        # replica wins — at boot, on failover, on rebalance — runs the
        # PR 5 cold-start adoption pass scoped to that shard's keys
        # BEFORE the shard is served (the live-handoff contract), then a
        # resync wave re-enqueues the moved keys into running controllers.
        # Losing a shard fences its dispatcher lanes.
        from tpu_composer.runtime.shards import shard_for

        shard_elector.on_acquire.append(
            lambda wins: adopt_pending_ops(
                client, fabric, dispatcher,
                shards=set(wins), num_shards=num_shards,
            )
        )
        shard_elector.on_ready.append(
            lambda shards: mgr.resync(
                lambda key, _s=frozenset(shards):
                shard_for(key, num_shards) in _s
            )
        )
        if dispatcher is not None:
            shard_elector.on_lose.append(
                lambda shard, reason: dispatcher.abandon_unowned()
            )
    else:
        mgr.add_startup_hook(
            lambda: adopt_pending_ops(client, fabric, dispatcher)
        )
    from tpu_composer.controllers.request_controller import (
        MigrateConfig,
        RepairConfig,
        RequestTiming,
    )
    from tpu_composer.controllers.resource_controller import ResourceTiming
    from tpu_composer.scheduler import ClusterScheduler, DefragLoop

    migrate_on = getattr(args, "migrate", True)
    # Defrag executor mode follows the migration switch: with the verb on,
    # executed plans become live make-before-break moves (safe against
    # running workloads); the escape hatch restores delete/re-solve.
    scheduler = ClusterScheduler(
        client, defrag_mode="migrate" if migrate_on else "delete",
        decisions=decisions_on, recorder=mgr.recorder,
        native_sched=getattr(args, "native_sched", None),
    )
    # Which kernel decisions will actually run on: "native" (packed
    # snapshot + libtpusched.so), "python" (snapshot, pure-Python port),
    # or "legacy" (per-decision store walks) — the fallback chain is
    # silent by design, so say where it landed.
    logging.getLogger("setup").info(
        "placement engine kernel: %s", scheduler.engine.kernel_kind
    )
    if scheduler.ledger is not None:
        # /debug/scheduler/explain/<name> + the crash-hook dump handle.
        mgr.decisions = scheduler.ledger
        if slo_engine is not None:
            # Queue-wait SLO breaches name their probable cause: the
            # dominant binding resource among recent hold-backs.
            slo_engine.annotators["queue_wait_p99"] = (
                scheduler.ledger.dominant_hold_back_reason
            )
        if fleet_plane is not None:
            fleet_plane.slo.annotators["fleet_queue_wait_p99"] = (
                scheduler.ledger.dominant_hold_back_reason
            )
    if decisions_on:
        from tpu_composer.runtime.capacity import CapacityObservatory

        capacity_obs = CapacityObservatory(
            client, scheduler.engine, goodput=goodput_tracker,
            period=getattr(args, "capacity_sample_period", 5.0),
        )
        mgr.capacity = capacity_obs
        mgr.add_runnable(capacity_obs.run)
    repair_cfg = RepairConfig(
        breaker_fraction=getattr(args, "repair_breaker_fraction", 0.5),
        breaker_min_members=getattr(args, "repair_breaker_min_members", 4),
        min_degraded_seconds=getattr(args, "repair_dwell", 0.0),
    )
    migrate_cfg = MigrateConfig(
        enabled=migrate_on,
        max_concurrent=max(1, getattr(args, "migrate_max_concurrent", 2)),
        breaker_fraction=getattr(args, "migrate_breaker_fraction", 0.25),
    )
    # TPUC_POLL_SCALE: one multiplier over the reconcilers' lifecycle
    # requeue cadences (attach/visibility/detach/busy/cleanup re-polls).
    # Production runs at 1.0. The proc-mode harnesses (fleet/proc.py,
    # bench_proc_scaling, make proc-smoke) shrink it so a real-process
    # replica's measured throughput is its reconcile capacity, not the
    # polling latency floor — the same cadences every in-proc bench tunes
    # through RequestTiming/ResourceTiming directly. Event-driven safety
    # nets (running_poll, health_poll) stay unscaled on purpose.
    try:
        poll_scale = float(os.environ.get("TPUC_POLL_SCALE", "") or 1.0)
    except ValueError:
        poll_scale = 1.0
    poll_scale = max(0.001, poll_scale)
    _rt, _qt = ResourceTiming(), RequestTiming()
    res_timing = ResourceTiming(
        attach_poll=_rt.attach_poll * poll_scale,
        visibility_poll=_rt.visibility_poll * poll_scale,
        detach_poll=_rt.detach_poll * poll_scale,
        detach_fast=_rt.detach_fast * poll_scale,
        busy_poll=_rt.busy_poll * poll_scale,
        health_failure_threshold=getattr(args, "health_failure_threshold", 3),
        node_degrade_threshold=getattr(args, "node_degrade_threshold", 3),
    )
    req_timing = RequestTiming(
        updating_poll=_qt.updating_poll * poll_scale,
        cleaning_poll=_qt.cleaning_poll * poll_scale,
        repair_poll=_qt.repair_poll * poll_scale,
    )
    req_rec = ComposabilityRequestReconciler(client, fabric,
                                             timing=req_timing,
                                             recorder=mgr.recorder,
                                             scheduler=scheduler,
                                             repair=repair_cfg,
                                             migrate=migrate_cfg,
                                             ownership=ownership)
    mgr.add_controller(req_rec)
    res_rec = ComposableResourceReconciler(client, fabric, agent,
                                           timing=res_timing,
                                           recorder=mgr.recorder,
                                           dispatcher=dispatcher,
                                           ownership=ownership,
                                           decision_ledger=scheduler.ledger)
    mgr.add_controller(res_rec)
    if migrate_on:
        # Node maintenance drains (controllers/maintenance.py): cordon +
        # drain-via-migration + deadline abort. Only with the verb on —
        # the escape hatch constructs no maintenance machinery at all.
        from tpu_composer.controllers.maintenance import (
            MaintenanceTiming,
            NodeMaintenanceReconciler,
        )

        mgr.add_controller(NodeMaintenanceReconciler(
            client,
            timing=MaintenanceTiming(
                default_deadline=getattr(
                    args, "migrate_drain_deadline", 1800.0
                ),
            ),
            recorder=mgr.recorder,
            ownership=ownership,
        ))
    if args.defrag_interval > 0:
        defrag_loop = DefragLoop(
            client, scheduler.defrag,
            period=args.defrag_interval,
            execute=args.defrag_execute,
            recorder=mgr.recorder,
            # Sharded: defrag plans over the whole cluster — exactly one
            # replica may run it. Shard 0's owner holds the duty; it fails
            # over with the lease like any other shard responsibility.
            gate=(
                (lambda: ownership.owns_shard(0))
                if ownership is not None else None
            ),
        )
        mgr.add_runnable(defrag_loop)
        # /debug/defrag (dry-run plan + skip reasons) reads this handle.
        mgr.defrag = defrag_loop
    mgr.add_runnable(UpstreamSyncer(
        client, fabric, period=args.sync_period,
        grace=args.sync_grace,
        recorder=mgr.recorder,
        ownership=ownership,
        # Outage ride-through: freeze the orphan grace clocks while the
        # store breaker is open — a dark store's diff must not reclaim
        # healthy mid-attach devices whose status writes couldn't land.
        suspend=storebreaker.is_open if storebreaker is not None else None,
        # Wire plane v2: while the fabric event session streams, the timed
        # get_resources() relist stretches to a safety net (same
        # multiplier the dispatcher's poll fallback uses) and inventory
        # events trigger immediate passes instead.
        session=session,
        fallback_multiplier=getattr(
            args, "fabric_poll_fallback_mult", 20.0
        ),
    ))
    if session is not None:
        from tpu_composer.agent.publisher import InventoryPublisher

        # Push-fed DRA publication repair: inventory events (not a poll)
        # re-check that every fabric-attached group is still published in
        # its node's ResourceSlice; the timed pass is the same demoted
        # safety net as the syncer's.
        mgr.add_runnable(InventoryPublisher(
            client, fabric, session=session,
            period=args.sync_period,
            fallback_multiplier=getattr(
                args, "fabric_poll_fallback_mult", 20.0
            ),
        ))
    # Event-driven visibility: /dev change events nudge the resource
    # controller instead of waiting out a poll quantum (BASELINE.md) —
    # inotify directly for a local agent, HTTP long-poll per node for the
    # cluster RemoteNodeAgent. Fakes keep the polling safety net only.
    if isinstance(agent, LocalNodeAgent):
        from tpu_composer.agent.watcher import DeviceEventWatcher

        mgr.add_runnable(DeviceEventWatcher(
            agent, res_rec, node_name=os.environ.get("NODE_NAME", "")
        ))
    else:
        from tpu_composer.agent.remote import RemoteNodeAgent
        from tpu_composer.agent.watcher import MultiNodeWatcher

        if isinstance(agent, RemoteNodeAgent):
            mgr.add_runnable(MultiNodeWatcher(agent, res_rec))
    if os.environ.get("ENABLE_WEBHOOKS", "").lower() != "false":
        register_validating_webhooks(client)
        if args.webhook_bind_address:
            # The AdmissionReview wire server (reference :9443 webhook
            # server, cmd/main.go:101-103): validating + pod-mutating
            # endpoints for the API server to call.
            from tpu_composer.admission.server import AdmissionServer

            def serve_webhooks(stop_event):
                certfile = args.webhook_cert or None
                log = logging.getLogger("webhook")
                if certfile:
                    # cert-manager writes the serving cert after our pod
                    # starts (the secret mount is optional) — hold the
                    # listener until it appears. The API server sees
                    # connection-refused and retries, so admission
                    # self-heals the moment the cert lands; serving plain
                    # HTTP instead would fail every TLS handshake forever.
                    warned = False
                    while not os.path.exists(certfile):
                        if not warned:
                            log.warning("waiting for webhook cert %s", certfile)
                            warned = True
                        if stop_event.wait(2.0):
                            return
                webhook = AdmissionServer(
                    client,
                    bind=args.webhook_bind_address,
                    certfile=certfile,
                    keyfile=(args.webhook_key or None) if certfile else None,
                )
                log.info("admission webhooks serving on %s (tls=%s)",
                         webhook.address, webhook.tls)
                webhook.run(stop_event)

            mgr.add_runnable(serve_webhooks)
    # Overload governor (runtime/overload.py): built last so every signal
    # source and stretchable cadence already exists. TPUC_OVERLOAD=0
    # constructs none of it — no governor thread, no shed gate, no
    # cadence stretching.
    if getattr(args, "overload", True):
        from tpu_composer.runtime.metrics import (
            fabric_breaker_state,
            slo_breached as _slo_breached_gauge,
        )
        from tpu_composer.runtime.overload import (
            OverloadGovernor,
            request_shed_gate,
        )

        governor = OverloadGovernor(
            period=getattr(args, "overload_period", 1.0),
            depth_warn=getattr(args, "overload_depth_warn", 256),
            depth_shed=getattr(args, "overload_depth_shed", 1024),
            stretch_factor=getattr(args, "overload_stretch", 4.0),
            shed_quantum=getattr(args, "overload_shed_quantum", 5.0),
            priority_cutoff=getattr(args, "overload_priority_cutoff", 50),
            ledger=scheduler.ledger,
            store_breaker=storebreaker,
            # The fabric breaker publishes per-endpoint state gauges
            # (0 closed / 1 open / 2 half-open): any fully-open endpoint
            # is a Warn signal.
            fabric_open=lambda: any(
                float(v) == 1.0 for _, v in fabric_breaker_state.state()
            ),
            slo_breached=lambda: any(
                float(v) >= 1.0 for _, v in _slo_breached_gauge.state()
            ),
            recorder=mgr.recorder,
        )
        governor.watchdog = watchdog
        # Live queue depths: queues are re-created by Controller.start(),
        # so close over the controller, not today's queue object.
        for c in mgr._controllers:
            governor.add_queue(lambda c=c: len(c.queue))
        # Non-critical cadences stretched in Warn/Shed (all read live
        # each tick by their loops).
        if mgr.defrag is not None:
            governor.stretch(mgr.defrag, "period")
        if mgr.capacity is not None:
            governor.stretch(mgr.capacity, "period")
        if fleet_plane is not None:
            governor.stretch(fleet_plane, "publish_period")
        if scheduler.ledger is not None:
            governor.stretch(scheduler.ledger, "hold_rescan_s")
        # The shed gate guards ONLY the request controller: resource
        # reconciles, health probes, detaches and repairs keep the tight
        # path no matter the state.
        req_rec.shed_gate = request_shed_gate(governor, client)
        mgr.overload = governor
        mgr.add_runnable(governor.run)
    if watchdog is not None:
        # Worker loops beat under their thread names (auto-registered on
        # first beat); the governor runnable is restartable — it is pure
        # policy and respawns safely mid-flight.
        for c in mgr._controllers:
            c.watchdog = watchdog
        if mgr.overload is not None:
            watchdog.register(
                "OverloadGovernor",
                stall_after=max(
                    watchdog.stall_after,
                    10.0 * getattr(args, "overload_period", 1.0),
                ),
                restartable=True,
            )
    return mgr


def trace_merge_main(argv: List[str]) -> int:
    """``tpu-composer trace-merge``: stitch per-replica trace files into
    one connected Chrome/Perfetto trace (see runtime.tracing.merge_chrome
    for the three passes: clock alignment, pid disambiguation, nonce-keyed
    flow stitching)."""
    import json

    from tpu_composer.runtime import tracing

    p = argparse.ArgumentParser(
        prog="tpu-composer trace-merge",
        description="merge per-replica Chrome trace files into one"
                    " stitched trace (open in Perfetto)",
    )
    p.add_argument("inputs", nargs="+",
                   help="per-replica trace JSON files (TPUC_TRACE_FILE"
                        " dumps / /debug/traces exports)")
    p.add_argument("--out", default="",
                   help="write the merged trace here (default: stdout)")
    args = p.parse_args(argv)
    try:
        merged = tracing.merge_files(args.inputs)
    except (OSError, ValueError, TypeError, KeyError, AttributeError) as e:
        # Unreadable file, non-JSON, the Array flavor, or events of an
        # unexpected shape — all surface as one clean CLI error.
        print(f"trace-merge: {e}", file=sys.stderr)
        return 1
    doc = json.dumps(merged)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc)
        meta = merged.get("metadata", {})
        print(
            f"{args.out}: {len(merged['traceEvents'])} events from"
            f" {meta.get('merged_files', len(args.inputs))} file(s),"
            f" {meta.get('stitched_flows', 0)} stitched flow(s)"
        )
    else:
        print(doc)
    return 0


def _format_decision(rec: dict) -> List[str]:
    """Human rendering of one DecisionRecord document."""
    out = [
        f"[{rec.get('at', '?')}] {rec.get('kind', '?')} ->"
        f" {rec.get('outcome', '?')}"
        + (f" (x{rec['repeats']})" if rec.get("repeats", 1) > 1 else "")
        + (f"  id={rec['decision_id']}" if rec.get("decision_id") else ""),
        f"  {rec.get('summary', '')}",
    ]
    binding = rec.get("binding")
    if binding:
        detail = ", ".join(
            f"{k}={v}" for k, v in binding.items() if k != "resource"
        )
        out.append(
            f"  binding: {binding.get('resource', '?')}"
            + (f" ({detail})" if detail else "")
        )
    if rec.get("victims"):
        out.append(
            f"  victims: {', '.join(rec['victims'])}"
            f" — {rec.get('victim_rationale', '')}"
        )
    inputs = rec.get("inputs")
    if inputs:
        out.append(
            f"  saw: {inputs.get('free_chips', '?')} free chips on"
            f" {inputs.get('schedulable_hosts', '?')} hosts,"
            f" fragmentation {inputs.get('fragmentation', '?')},"
            f" queue depth {inputs.get('queue_depth', '?')}"
        )
    rejected = [
        c for c in rec.get("candidates", []) if c.get("verdict") != "ok"
    ]
    if rejected:
        shown = ", ".join(
            f"{c['node']}: {c['verdict']}" for c in rejected[:8]
        )
        more = len(rejected) - 8
        out.append(
            "  rejected: " + shown + (f" (+{more} more)" if more > 0 else "")
        )
    if rec.get("nonces"):
        out.append(f"  executed by intents: {', '.join(rec['nonces'])}")
    return out


def explain_main(argv: List[str]) -> int:
    """``tpu-composer explain <cr>``: print the scheduler's decision ring
    for one request, from a running operator's health port or a
    $TPUC_DECISIONS_FILE crash dump."""
    import json
    import urllib.error
    import urllib.parse
    import urllib.request

    p = argparse.ArgumentParser(
        prog="tpu-composer explain",
        description="why did the scheduler place / queue / preempt this"
                    " request the way it did",
    )
    p.add_argument("name", help="ComposabilityRequest name")
    p.add_argument("--addr", default="127.0.0.1:8081",
                   help="running operator's health endpoint"
                        " (default 127.0.0.1:8081)")
    p.add_argument("--file", default="",
                   help="read a decision-ring dump (TPUC_DECISIONS_FILE /"
                        " --decisions-file output) instead of a live"
                        " operator")
    p.add_argument("--json", action="store_true",
                   help="print the raw JSON document instead of the"
                        " human rendering")
    args = p.parse_args(argv)
    if args.file:
        try:
            with open(args.file) as f:
                dump = json.load(f)
        except (OSError, ValueError) as e:
            print(f"explain: {e}", file=sys.stderr)
            return 1
        records = (dump.get("requests") or {}).get(args.name)
        if not records:
            print(f"explain: no decisions recorded for {args.name!r} in"
                  f" {args.file}", file=sys.stderr)
            return 1
        doc = {"request": args.name, "latest": records[-1],
               "decisions": records}
    else:
        url = (f"http://{args.addr}/debug/scheduler/explain/"
               f"{urllib.parse.quote(args.name)}")
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                doc = json.load(resp)
        except urllib.error.HTTPError as e:
            print(f"explain: {e.code} {e.reason} — {e.read().decode(errors='replace')}",
                  file=sys.stderr)
            return 1
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"explain: cannot reach {url}: {e}", file=sys.stderr)
            return 1
    if args.json:
        print(json.dumps(doc, indent=1))
        return 0
    print(f"{args.name}: {len(doc['decisions'])} recorded decision(s)")
    for rec in doc["decisions"]:
        for line in _format_decision(rec):
            print(line)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "trace-merge":
        return trace_merge_main(argv[1:])
    if argv and argv[0] == "explain":
        return explain_main(argv[1:])
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper()),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
        stream=sys.stderr,
    )
    log = logging.getLogger("setup")

    mgr = build_manager(args)

    stopping = []

    def handle_signal(signum, frame):
        if stopping:
            return
        stopping.append(signum)
        log.info("received signal %s, shutting down", signum)
        mgr.stop()

    signal.signal(signal.SIGTERM, handle_signal)
    signal.signal(signal.SIGINT, handle_signal)

    log.info(
        "starting manager (provider=%s, health=%s, leader_elect=%s)",
        os.environ.get("CDI_PROVIDER_TYPE", "MOCK"),
        args.health_probe_bind_address or "disabled",
        args.leader_elect,
    )
    mgr.start(workers_per_controller=args.workers)
    if getattr(args, "port_file", ""):
        # Written AFTER start so a :0 health bind reports its real port; the
        # tmp+rename makes the appearance of the file itself the readiness
        # signal a supervisor polls on (no half-written JSON window).
        doc = json.dumps({
            "pid": os.getpid(),
            "health_port": mgr.health_port,
            "replica_id": mgr.replica_id,
        })
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(doc + "\n")
        os.replace(tmp, args.port_file)
    mgr.wait()
    if mgr.lost_leadership:
        log.error("exiting: leadership lost (restart to rejoin as standby)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
