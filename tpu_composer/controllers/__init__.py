"""Orchestration controllers.

Reference analog: internal/controller — ComposabilityRequest reconciler
(request → slice → per-host children), ComposableResource reconciler
(per chip-group lifecycle), UpstreamSyncer (fabric↔local anti-drift).
"""

from tpu_composer.controllers.maintenance import (
    MaintenanceTiming,
    NodeMaintenanceReconciler,
)
from tpu_composer.controllers.resource_controller import (
    ComposableResourceReconciler,
    ResourceTiming,
)
from tpu_composer.controllers.request_controller import (
    ComposabilityRequestReconciler,
    MigrateConfig,
    RequestTiming,
)
from tpu_composer.controllers.syncer import UpstreamSyncer

__all__ = [
    "ComposableResourceReconciler",
    "ResourceTiming",
    "ComposabilityRequestReconciler",
    "MigrateConfig",
    "RequestTiming",
    "MaintenanceTiming",
    "NodeMaintenanceReconciler",
    "UpstreamSyncer",
]
