# tpuc: ignore-file[fabric-mutation-path] — the adoption pass is the ONE
# designated raw-mutation path: it runs post-leader-acquire and
# pre-controller-start, before any shard lease exists to fence against,
# and its verbs are idempotent completion re-reads keyed by the durable
# intent nonce (double-issue is harmless by construction).
"""Cold-start adoption of in-flight fabric intents.

A process crash (or hard leader failover) loses every in-memory trace of
fabric work: the dispatcher's lanes and parked outcomes, reconcile workers
mid-call, completion latches. What survives is (a) the durable
``status.pending_op`` intent records the resource controller writes BEFORE
any fabric mutation, and (b) the fabric's own state. This pass — run by the
Manager after leader acquisition and before any controller starts — diffs
the two and classifies every in-flight op, the restart/adoption hard case
composable-orchestration work keeps rediscovering (arXiv:2404.06467 §V,
Funky/arXiv:2510.15755):

==============================  ==========================================
classification                  action
==============================  ==========================================
completed-but-unrecorded add    idempotent completion re-read
                                (``add_resource`` on an attachment the
                                fabric already holds — the reference's
                                ADD_COMPLETE re-scan), fold device ids +
                                cdi id into status, retire the intent
never-issued add                clear the intent; the normal reconcile
                                re-submits with fresh intent and normal
                                attach-budget accounting
fabric-async add in progress    hand to the dispatcher's re-poll pass
                                (submit; the provider's wait sentinel
                                parks it for shared per-node re-polls)
completed-but-unrecorded        retire the intent; the Detaching
remove                          reconcile's idempotent no-op remove
                                finishes the state machine
remove still in flight /        adopt any fabric-known device ids into
not yet effective               status, re-submit through the dispatcher
                                (idempotent), keep the intent
quarantined / deleted owner     retire stale intents without touching the
                                fabric (budget + quarantine accounting is
                                never rewritten by adoption)
==============================  ==========================================

Attach-budget and quarantine accounting are preserved bit-for-bit: adoption
never increments ``attach_attempts``, never quarantines, and never clears
either field — a probe failure simply leaves the retry (and its counting)
to the normal reconcile path, exactly like pre-crash failures that were
only floor-persisted.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_composer.api.types import (
    ComposableResource,
    RESOURCE_STATE_DETACHING,
)
from tpu_composer.fabric.provider import (
    FabricDevice,
    FabricError,
    WaitingDeviceAttaching,
    WaitingDeviceDetaching,
)
from tpu_composer.runtime import tracing
from tpu_composer.runtime.metrics import adoption_ops_total
from tpu_composer.runtime.store import ConflictError, NotFoundError, StoreError

log = logging.getLogger("adoption")


@dataclass
class AdoptionReport:
    """What the pass did, by resource name (introspection for logs/tests)."""

    adopted: List[str] = field(default_factory=list)  # results folded into status
    reissued: List[str] = field(default_factory=list)  # intent cleared; reconcile re-submits
    repolled: List[str] = field(default_factory=list)  # handed to dispatcher re-poll
    cleared: List[str] = field(default_factory=list)  # stale/moot intent retired
    deferred: List[str] = field(default_factory=list)  # left to normal reconcile
    errors: List[str] = field(default_factory=list)

    @property
    def total(self) -> int:
        return (
            len(self.adopted) + len(self.reissued) + len(self.repolled)
            + len(self.cleared) + len(self.deferred) + len(self.errors)
        )


def _devices_for(
    res: ComposableResource,
    by_owner: Dict[str, List[FabricDevice]],
    unowned: List[FabricDevice],
) -> List[FabricDevice]:
    """The fabric devices this resource's attach produced, if any.

    Exact when the provider reports ``resource_name`` (InMemoryPool, REST
    pool services with the field); otherwise falls back to matching the
    (slice, node) pair or the device ids already recorded in status —
    providers that report neither classify as not-attached and converge
    through the idempotent re-submit path instead.
    """
    exact = by_owner.get(res.metadata.name)
    if exact:
        return exact
    if res.spec.slice_name:
        return [
            d for d in unowned
            if d.slice_name == res.spec.slice_name
            and d.node == res.spec.target_node
        ]
    if res.status.device_ids:
        known = set(res.status.device_ids)
        return [d for d in unowned if d.device_id in known]
    return []


def adopt_pending_ops(
    store, fabric, dispatcher=None, shards=None, num_shards: int = 1
) -> AdoptionReport:
    """One cold-start pass over every durable ``pending_op`` record.

    Runs post-leader-acquire, pre-controller-start (Manager wiring): by the
    time the first reconcile fires, every surviving intent is either
    resolved into status, cleared for clean re-submission, or already
    re-polling inside the dispatcher.

    With ``shards`` (a set of shard indices) and ``num_shards``, the pass
    is SCOPED: only intents whose resource key hashes into one of the
    given shards are classified. This is the shard-acquisition handoff —
    a shard migration is a cold-start adoption scoped to the moved keys,
    so failover and rebalancing reuse exactly the machinery the
    kill–restart soak proves. The default (``shards=None``) scans
    everything, bit-identical to the single-leader pass.
    """
    report = AdoptionReport()
    try:
        resources = store.list(ComposableResource)
    except StoreError as e:
        log.warning("adoption skipped: listing resources failed: %s", e)
        report.errors.append(f"list: {e}")
        return report
    pending = [r for r in resources if r.status.pending_op is not None]
    if shards is not None:
        from tpu_composer.runtime.shards import shard_for

        pending = [
            r for r in pending
            if shard_for(r.metadata.name, num_shards) in shards
        ]
    if not pending:
        return report

    try:
        listing = fabric.get_resources()
    except FabricError as e:
        # Fabric dark at startup: leave every intent in place — the normal
        # reconcile path (breaker + backoff) owns the retry story.
        log.warning("adoption deferred: fabric listing failed: %s", e)
        for r in pending:
            report.deferred.append(r.metadata.name)
            adoption_ops_total.inc(
                verb=r.status.pending_op.verb, outcome="deferred"
            )
        return report

    by_owner: Dict[str, List[FabricDevice]] = {}
    unowned: List[FabricDevice] = []
    for dev in listing:
        if dev.resource_name:
            by_owner.setdefault(dev.resource_name, []).append(dev)
        else:
            unowned.append(dev)

    for res in pending:
        verb = res.status.pending_op.verb
        try:
            # The adoption span JOINS the op's pre-crash trace: the durable
            # nonce is the trace id, so a Perfetto export shows the dead
            # incarnation's reconcile/dispatch spans and this successor's
            # adoption span under one trace_id — the cross-crash continuity
            # the kill–restart soak asserts. The span additionally names
            # the adopting replica so a merged fleet trace reads "intent by
            # A, adopted by B" without decoding pseudo-pids.
            with tracing.span(
                "adopt", cat="adoption", resource=res.metadata.name,
                verb=verb,
                replica=tracing.current_replica() or "",
                ctx=tracing.TraceContext(trace_id=res.status.pending_op.nonce),
            ) as sp:
                outcome = _adopt_one(
                    store, fabric, dispatcher, res,
                    _devices_for(res, by_owner, unowned),
                )
                sp["outcome"] = outcome
        except (ConflictError, NotFoundError):
            # Another writer (a standby that just lost, a racing delete)
            # moved the object — the reconcile path owns it now.
            outcome = "deferred"
        except StoreError as e:
            log.warning("adoption of %s failed: %s", res.metadata.name, e)
            outcome = "error"
            report.errors.append(f"{res.metadata.name}: {e}")
        if outcome != "error":
            getattr(report, {
                "adopted": "adopted", "reissue": "reissued",
                "repoll": "repolled", "cleared": "cleared",
                "deferred": "deferred",
            }[outcome]).append(res.metadata.name)
        adoption_ops_total.inc(verb=verb, outcome=outcome)

    if report.total:
        log.info(
            "adoption: %d intent(s) — %d adopted, %d reissued, %d repolling,"
            " %d cleared, %d deferred, %d errors",
            report.total, len(report.adopted), len(report.reissued),
            len(report.repolled), len(report.cleared), len(report.deferred),
            len(report.errors),
        )
    return report


def _adopt_one(store, fabric, dispatcher, res, devices) -> str:
    """Classify and act on one pending intent; returns the outcome label."""
    po = res.status.pending_op
    name = res.metadata.name

    if po.verb == "add":
        if res.status.quarantined:
            # Terminal until the owner reallocates: never re-probe (let
            # alone re-issue) an attach the budget machinery retired.
            _clear_intent(store, res)
            return "cleared"
        if devices:
            # Completed but unrecorded: the fabric holds the attachment,
            # the crash ate the status write. The idempotent completion
            # re-read returns the full AttachResult (incl. cdi id, which
            # the listing does not carry).
            try:
                result = fabric.add_resource(res)
            except WaitingDeviceAttaching:
                return _hand_to_repoll(dispatcher, "add", res)
            except FabricError as e:
                log.warning("adoption re-read of %s failed: %s", name, e)
                return "deferred"  # intent kept; reconcile retries + counts
            res.status.device_ids = list(result.device_ids)
            res.status.cdi_device_id = result.cdi_device_id
            res.status.pending_op = None
            store.update_status(res)
            log.info("adopted completed attach %s (%d device(s))",
                     name, len(result.device_ids))
            return "adopted"
        if res.being_deleted:
            # Nothing materialized and the owner is going away: retire the
            # intent; the deletion path needs no fabric work. (A fabric
            # async attach that still lands later is the syncer's orphan
            # sweep to reclaim — its grace clock now survives restarts.)
            _clear_intent(store, res)
            return "cleared"
        # Not (visibly) attached: either never issued, or async-in-
        # progress. One direct probe tells them apart — the idempotent
        # contract makes it safe either way, and a sync provider answering
        # with the result is the same terminal state reconcile wanted.
        try:
            result = fabric.add_resource(res)
        except WaitingDeviceAttaching:
            # The fabric is (now) working on it — the dispatcher's shared
            # per-node re-poll pass takes over.
            return _hand_to_repoll(dispatcher, "add", res)
        except FabricError as e:
            # Never issued as far as anyone can prove, and the fabric is
            # not accepting right now: clear the intent so the reconcile
            # re-submits under its own (budget-counted) retry loop.
            log.warning(
                "adoption probe for %s failed (%s); clearing intent for"
                " normal re-submission", name, e,
            )
            _clear_intent(store, res)
            return "reissue"
        res.status.device_ids = list(result.device_ids)
        res.status.cdi_device_id = result.cdi_device_id
        res.status.pending_op = None
        store.update_status(res)
        return "adopted"

    # verb == "remove"
    if devices:
        # Fabric still holds chips for this resource: the detach never
        # became effective (or is async mid-flight). Make sure status
        # knows every id the fabric reports (a crash can predate the id
        # adoption), then re-drive through the dispatcher's re-poll pass.
        known = set(res.status.device_ids)
        fabric_ids = [d.device_id for d in devices]
        if not known.issuperset(fabric_ids):
            res.status.device_ids = sorted(known.union(fabric_ids))
            res = store.update_status(res)
        return _hand_to_repoll(dispatcher, "remove", res)
    # Nothing left at the fabric: the detach completed but the crash ate
    # the Deleting transition. Retire the intent; the Detaching reconcile
    # re-runs its (idempotent) tail and finishes the state machine.
    _clear_intent(store, res)
    if res.status.state == RESOURCE_STATE_DETACHING:
        log.info("detach of %s already effective at the fabric; reconcile"
                 " completes the teardown", name)
    return "cleared"


def _clear_intent(store, res) -> None:
    res.status.pending_op = None
    store.update_status(res)


def _hand_to_repoll(dispatcher, verb, res) -> str:
    """Submit an in-progress op to the dispatcher so its shared per-node
    re-poll pass (not a cold 30s-style requeue) drives it to completion.
    Without a dispatcher the normal reconcile poll timers take over."""
    if dispatcher is None:
        return "deferred"
    try:
        if verb == "add":
            dispatcher.add_resource(res)
        else:
            dispatcher.remove_resource(res)
    except (WaitingDeviceAttaching, WaitingDeviceDetaching):
        pass  # Dispatched*/Waiting* — submission parked, exactly the goal
    return "repoll"
