"""NodeMaintenance reconciler — cordon, drain via live migration, abort.

The declarative node-drain verb (api/maintenance.py): an operator creates a
NodeMaintenance naming a host; this controller cordons it with the durable
whole-node quarantine marker (distinct ``maintenance:<name>`` reason) and
marks every live member on it for evacuation. The owning requests' live-
migration drivers (request_controller._drive_migrations) do the actual
make-before-break moves — this controller only CLAIMS members and watches
the node empty, so the surge budgets and the fleet migration breaker bound
a drain exactly like any other evacuation.

State machine::

    "" ── cordon (quarantine marker) ──▶ Cordoned ──▶ Draining
                                                        │
                     node empty of members ◀────────────┤
                               │                        │ deadline expired
                               ▼                        ▼
                            Drained                  Aborted
                     (marker STAYS until           (unstarted marks
                      the object is deleted         withdrawn, marker
                      — the maintenance window)     cleared — capacity
                                                    returns)

Deleting the object at ANY point uncordons: evacuation marks this drain
placed are withdrawn from members not yet moving, and the maintenance
quarantine marker is cleared (markers placed by the attach-budget or
escalation paths are never touched — only our own ``maintenance:`` reason).
In-flight migrations are left to complete: aborting a half-cutover move
would be strictly worse than finishing it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from tpu_composer.agent.publisher import (
    DevicePublisher,
    node_quarantine_name,
)
from tpu_composer.api.dra import DeviceTaintRule
from tpu_composer.api.maintenance import (
    MAINTENANCE_REASON_PREFIX,
    MAINTENANCE_STATE_ABORTED,
    MAINTENANCE_STATE_CORDONED,
    MAINTENANCE_STATE_DRAINED,
    MAINTENANCE_STATE_DRAINING,
    MAINTENANCE_STATE_EMPTY,
    NodeMaintenance,
)
from tpu_composer.api.meta import now_iso, parse_iso
from tpu_composer.api.types import (
    ANNOTATION_EVACUATE,
    ANNOTATION_EVACUATE_TARGET,
    ComposableResource,
    FINALIZER,
    LABEL_READY_TO_DETACH,
    MIGRATE_TRIGGER_MAINTENANCE,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.runtime.controller import Controller, Result
from tpu_composer.runtime.events import WARNING, EventRecorder
from tpu_composer.runtime.metrics import (
    migrations_total,
    node_maintenances_active,
)
from tpu_composer.runtime.store import (
    ConflictError,
    NotFoundError,
    Store,
    WatchEvent,
)


@dataclass
class MaintenanceTiming:
    #: Drain-progress safety-net poll; the ComposableResource watch is the
    #: primary wake signal (a member leaving the node re-enqueues).
    drain_poll: float = 0.5
    #: Deadline applied when spec.deadline_seconds == 0
    #: (--migrate-drain-deadline); <= 0 means no default deadline.
    default_deadline: float = 1800.0


def evacuate_value(maintenance_name: str) -> str:
    return f"{MIGRATE_TRIGGER_MAINTENANCE}:{maintenance_name}"


class NodeMaintenanceReconciler(Controller):
    primary_kind = "NodeMaintenance"

    def __init__(
        self,
        store: Store,
        timing: Optional[MaintenanceTiming] = None,
        recorder: Optional[EventRecorder] = None,
        publisher=None,
        ownership=None,
    ) -> None:
        super().__init__(store, ownership=ownership)
        self.timing = timing or MaintenanceTiming()
        self.recorder = recorder or EventRecorder()
        self.publisher = publisher or DevicePublisher(store)
        # Drain progress is event-driven: any member change on a drained
        # node wakes its maintenance object (DELETED events especially —
        # the "node empty" edge must not wait out drain_poll).
        self.watch("ComposableResource", mapper=self._map_member_event)

    def _map_member_event(self, ev: WatchEvent) -> List[str]:
        node = ev.obj.spec.target_node
        if not node:
            return []
        return [
            m.metadata.name
            for m in self.store.list(NodeMaintenance)
            if m.spec.node_name == node
        ]

    # ------------------------------------------------------------------
    def reconcile(self, name: str) -> Result:
        m = self.store.try_get(NodeMaintenance, name)
        if m is None:
            self._refresh_gauge()
            return Result()
        if m.being_deleted:
            return self._handle_deleted(m)
        state = m.status.state
        if state == MAINTENANCE_STATE_EMPTY:
            return self._handle_none(m)
        if state in (MAINTENANCE_STATE_CORDONED, MAINTENANCE_STATE_DRAINING):
            return self._handle_draining(m)
        if state == MAINTENANCE_STATE_ABORTED:
            # Level-triggered sweep: a mark withdrawal that lost a write
            # conflict during _abort must not leave a live evacuation mark
            # on an uncordoned node — the migration driver would execute
            # the very move the abort cancelled. Member watch events (and
            # any reconcile of this object) retry the withdrawal.
            self._withdraw_marks(m)
        # Drained / Aborted: terminal until deletion (the window).
        self._refresh_gauge()
        return Result()

    # ------------------------------------------------------------------
    def _members(self, node: str) -> List[ComposableResource]:
        """Live members still occupying the node. Syncer detach-CRs
        (ready-to-detach orphan reclaimers) are already teardown-bound and
        never block a drain."""
        return [
            c for c in self.store.list(ComposableResource)
            if c.spec.target_node == node
            and not c.being_deleted
            and not c.metadata.labels.get(LABEL_READY_TO_DETACH)
        ]

    def _refresh_gauge(self) -> None:
        active = sum(
            1 for m in self.store.list(NodeMaintenance)
            if not m.being_deleted and m.status.state in (
                MAINTENANCE_STATE_CORDONED, MAINTENANCE_STATE_DRAINING,
            )
        )
        node_maintenances_active.set(float(active))

    def _opted_out(self, c: ComposableResource) -> bool:
        """True when the member's owner opted out of the replacement
        machinery (repairPolicy=None) — live migration rides it, so such
        members are never claimed for evacuation."""
        from tpu_composer.api.types import (
            LABEL_MANAGED_BY,
            REPAIR_NONE,
            ComposabilityRequest,
        )

        owner = c.metadata.labels.get(LABEL_MANAGED_BY, "")
        if not owner:
            return True  # standalone CR: nothing drives a migration for it
        req = self.store.try_get(ComposabilityRequest, owner)
        return req is None or req.spec.repair_policy == REPAIR_NONE

    def _own_marker(self, node: str):
        """This drain's quarantine marker, or None when the node is
        unmarked OR carries someone else's marker (attach-budget /
        escalation reasons are never ours to clear)."""
        rule = self.store.try_get(DeviceTaintRule, node_quarantine_name(node))
        if rule is None:
            return None
        if not rule.spec.reason.startswith(MAINTENANCE_REASON_PREFIX):
            return None
        return rule

    # ------------------------------------------------------------------
    def _handle_none(self, m: NodeMaintenance) -> Result:
        if m.add_finalizer(FINALIZER):
            m = self.store.update(m)
        # Cordon FIRST (idempotent create; a marker already present from
        # the escalation path serves the same purpose and stays theirs),
        # then record the durable deadline clock. Ordered so a crash
        # between the two re-runs the no-op cordon, never drains an
        # uncordoned node.
        self.publisher.quarantine_node(
            m.spec.node_name,
            evacuate_value(m.name)
            + (f" ({m.spec.reason})" if m.spec.reason else ""),
        )
        m.status.state = MAINTENANCE_STATE_CORDONED
        m.status.started_at = now_iso()
        m.status.remaining = len(self._members(m.spec.node_name))
        try:
            self._update_status(m)
        except NotFoundError:
            return Result()
        self.recorder.event(
            m, "Normal", "Cordoned",
            f"node {m.spec.node_name} cordoned for maintenance"
            f" ({m.status.remaining} member(s) to evacuate)",
        )
        self._refresh_gauge()
        return Result(requeue_after=0.0)

    def _handle_draining(self, m: NodeMaintenance) -> Result:
        node = m.spec.node_name
        members = self._members(node)
        prev_remaining = m.status.remaining
        changed = False

        if not members:
            m.status.state = MAINTENANCE_STATE_DRAINED
            m.status.evacuated += max(0, prev_remaining)
            m.status.remaining = 0
            m.status.message = (
                "node empty; maintenance window open — delete this"
                " NodeMaintenance to uncordon"
            )
            try:
                self._update_status(m)
            except NotFoundError:
                return Result()
            self.recorder.event(
                m, "Normal", "Drained",
                f"node {node} drained ({m.status.evacuated} member(s)"
                " evacuated); hardware work can start",
            )
            self._refresh_gauge()
            return Result()

        # Deadline: the drain may not run forever — capacity must return.
        deadline = m.spec.deadline_seconds
        if deadline == 0:
            deadline = self.timing.default_deadline
        if deadline > 0 and m.status.started_at:
            try:
                elapsed = (
                    parse_iso(now_iso()) - parse_iso(m.status.started_at)
                ).total_seconds()
            except ValueError:
                elapsed = 0.0
            if elapsed > deadline:
                return self._abort(m, members, elapsed, deadline)

        # Claim members for evacuation. Only Online members are marked
        # (Degraded/Repairing belong to the repair driver, which already
        # places replacements OFF the cordoned node; Attaching members are
        # claimed once they come up). Members whose owner opted out of the
        # replacement machinery (repairPolicy=None) are never claimed —
        # the migration driver would refuse the move anyway; they hold the
        # drain until the deadline aborts it, and the status message says
        # why. Marks carry this drain's identity so cleanup withdraws
        # only its own.
        unmigratable = 0
        for c in members:
            if c.status.state != RESOURCE_STATE_ONLINE:
                continue
            if self._opted_out(c):
                unmigratable += 1
                continue
            if c.metadata.annotations.get(ANNOTATION_EVACUATE):
                continue  # already claimed (by us, defrag, or escalation)
            c.metadata.annotations[ANNOTATION_EVACUATE] = evacuate_value(m.name)
            try:
                self.store.update(c)
            except (ConflictError, NotFoundError):
                pass  # re-claimed next pass

        if m.status.state != MAINTENANCE_STATE_DRAINING:
            m.status.state = MAINTENANCE_STATE_DRAINING
            changed = True
        if len(members) != prev_remaining:
            m.status.evacuated += max(0, prev_remaining - len(members))
            m.status.remaining = len(members)
            changed = True
        msg = (
            f"{len(members)} member(s) remaining on {node}"
            f" ({sum(1 for c in members if c.status.state == 'Migrating')}"
            " migrating"
            + (f", {unmigratable} unmigratable: repairPolicy=None"
               if unmigratable else "")
            + ")"
        )
        if m.status.message != msg:
            m.status.message = msg
            changed = True
        if changed:
            try:
                self._update_status(m)
            except NotFoundError:
                return Result()
        self._refresh_gauge()
        return Result(requeue_after=self.timing.drain_poll)

    def _withdraw_marks(self, m: NodeMaintenance, count: bool = False) -> int:
        """Withdraw this drain's unstarted (Online-member) evacuation
        marks. Idempotent and level-triggered: the Aborted sweep re-runs
        it until every mark is gone, so a lost write conflict here is a
        retry, never a leak. Members already mid-move (Migrating) keep
        their marks and finish — their make-before-break is past the
        point where stopping helps anyone."""
        withdrawn = 0
        for c in self._members(m.spec.node_name):
            if (
                c.metadata.annotations.get(ANNOTATION_EVACUATE)
                == evacuate_value(m.name)
                and c.status.state == RESOURCE_STATE_ONLINE
            ):
                c.metadata.annotations.pop(ANNOTATION_EVACUATE, None)
                c.metadata.annotations.pop(ANNOTATION_EVACUATE_TARGET, None)
                try:
                    self.store.update(c)
                    withdrawn += 1
                    if count:
                        migrations_total.inc(
                            trigger=MIGRATE_TRIGGER_MAINTENANCE,
                            outcome="aborted",
                        )
                except (ConflictError, NotFoundError):
                    pass  # the Aborted sweep / deleted-path retries
        return withdrawn

    def _abort(
        self, m: NodeMaintenance, members, elapsed: float, deadline: float
    ) -> Result:
        """Deadline expired: withdraw this drain's unstarted evacuation
        marks, uncordon, park in Aborted (whose reconcile keeps sweeping
        leftover marks until they are gone)."""
        node = m.spec.node_name
        withdrawn = self._withdraw_marks(m, count=True)
        if self._own_marker(node) is not None:
            self.publisher.clear_node_quarantine(node)
        m.status.state = MAINTENANCE_STATE_ABORTED
        m.status.remaining = len(members)
        m.status.message = (
            f"drain deadline expired after {elapsed:.0f}s"
            f" (deadline {deadline:.0f}s) with {len(members)} member(s)"
            f" remaining; {withdrawn} evacuation mark(s) withdrawn and the"
            " node uncordoned"
        )
        try:
            self._update_status(m)
        except NotFoundError:
            return Result()
        self.recorder.event(m, WARNING, "DrainAborted", m.status.message)
        self.log.warning("%s: %s", m.name, m.status.message)
        self._refresh_gauge()
        return Result()

    def _handle_deleted(self, m: NodeMaintenance) -> Result:
        """Uncordon on deletion, whatever state the drain reached: withdraw
        this drain's remaining marks, clear our marker, release the
        finalizer."""
        node = m.spec.node_name
        for c in self._members(node):
            if (
                c.metadata.annotations.get(ANNOTATION_EVACUATE)
                == evacuate_value(m.name)
            ):
                c.metadata.annotations.pop(ANNOTATION_EVACUATE, None)
                c.metadata.annotations.pop(ANNOTATION_EVACUATE_TARGET, None)
                try:
                    self.store.update(c)
                except (ConflictError, NotFoundError):
                    pass
        if self._own_marker(node) is not None:
            self.publisher.clear_node_quarantine(node)
        if m.remove_finalizer(FINALIZER):
            try:
                self.store.update(m)
            except NotFoundError:
                pass  # purged concurrently — done
        self._refresh_gauge()
        return Result()

    def _update_status(self, m: NodeMaintenance) -> None:
        try:
            self.store.update_status(m)
        except ConflictError:
            pass  # level-derived; the requeue recomputes from fresh state
